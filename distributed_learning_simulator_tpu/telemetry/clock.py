"""One clock for every timing subsystem (spans, phases, stream records).

Before this module each timing consumer hand-rolled its own calls —
``telemetry/phases.py`` used ``time.perf_counter()``, the streaming
prefetch records used another set, the chaos/report tooling a third.
That worked while every number stayed host-local, but the distributed
tracing layer (``telemetry/spans.py``) must relate timestamps ACROSS
hosts, which needs one explicit convention:

* :func:`monotonic` — the intra-host span/phase clock. Monotonic,
  unaffected by NTP steps; meaningless across hosts (each host's
  monotonic epoch is arbitrary, typically boot time).
* :func:`wall` — UNIX epoch seconds. Comparable across hosts up to NTP
  error; used ONLY to anchor each host's monotonic epoch in the span
  journal header, never for durations.

A journal header records the pair ``(epoch_wall, epoch_mono)`` sampled
back-to-back plus the barrier-estimated ``clock_offset_s`` vs host 0
(``parallel/multihost.estimate_clock_alignment``).  The stitcher maps a
host-local monotonic stamp ``t`` onto the shared timeline as::

    aligned = (t - epoch_mono) + epoch_wall - clock_offset_s

:func:`align` implements exactly that (pure math, jax-free) so the
recorder, the stitcher, and the tests cannot drift apart on sign
conventions.
"""

from __future__ import annotations

import time


def monotonic() -> float:
    """Host-local monotonic seconds (``time.perf_counter``): durations
    and span begin/end stamps. Never comparable across hosts."""
    return time.perf_counter()


def wall() -> float:
    """UNIX epoch seconds (``time.time``): cross-host anchoring only —
    NTP may step it, so never subtract two wall stamps for a duration."""
    return time.time()


def align(t_mono: float, epoch_mono: float, epoch_wall: float,
          clock_offset_s: float = 0.0) -> float:
    """Map a host-local monotonic stamp onto the shared wall timeline.

    ``clock_offset_s`` is THIS host's wall-clock offset relative to host
    0 (positive = this host's wall clock reads ahead), as estimated by
    ``estimate_clock_alignment`` — subtracting it expresses the stamp in
    host 0's wall time, the common axis all journals stitch onto.
    """
    return (t_mono - epoch_mono) + epoch_wall - clock_offset_s
