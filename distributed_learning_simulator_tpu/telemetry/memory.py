"""HBM watermark sampling: the ONE ``memory_stats()`` probe.

Device memory statistics come from the PJRT plugin and are optional —
CPU returns ``None``, and some plugin versions omit individual keys.
Every consumer (the round loop's per-round watermark, the chunk
auto-sizer's budget model, scripts/measure_gtg_scale.py) goes through
these helpers so the graceful-``None`` contract lives in one place.
"""

from __future__ import annotations

import jax


def device_memory_stats(device=None) -> dict | None:
    """Raw ``memory_stats()`` dict for ``device`` (default: first local
    device), or ``None`` when the backend doesn't report memory stats."""
    try:
        if device is None:
            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    return dict(stats) if stats else None


def peak_hbm_bytes(device=None) -> int | None:
    """High-water mark of device memory in use (``peak_bytes_in_use``),
    or ``None`` when unavailable. On TPU this is cumulative since process
    start — per-round samples are monotone, and the per-run watermark is
    the last round's value."""
    stats = device_memory_stats(device)
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    return int(peak) if peak else None


def hbm_limit_bytes(device=None) -> int | None:
    """Usable device memory capacity (``bytes_limit``), or ``None`` when
    unavailable. Feeds the footprint/budget model shared by the chunk
    auto-sizer, the OOM hint, and the materializing-path feasibility
    refusal (simulator._device_budget_bytes)."""
    stats = device_memory_stats(device)
    if not stats:
        return None
    limit = stats.get("bytes_limit")
    return int(limit) if limit else None
