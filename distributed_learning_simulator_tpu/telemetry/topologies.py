"""Checked-in TPU topology table for the predictive cost model.

The roofline model (telemetry/costmodel.py) prices a traced-op ledger
against hardware it has never run on; this module is the ONE place those
hardware constants live, so adding a topology is a one-line table edit
(docs/OBSERVABILITY.md § Cost model — "how to add a topology").

Numbers are NOMINAL datasheet peaks (per chip): bf16 MXU TFLOP/s, HBM
GB/s (SI), aggregate off-chip ICI GB/s, and an on-demand USD price per
chip-hour. Real programs reach a measured FRACTION of these peaks — the
fitted efficiency factors in costmodel.DEFAULT_EFFICIENCY, calibrated
against this repo's measured single-chip rounds (docs/PERFORMANCE.md
§ Predicted pod-scale cost) — so the table itself never needs
"derating"; keep it at datasheet values.

``cpu-host`` models the CI / dev-box fallback (virtual CPU mesh): a
self-hosted host priced at zero, present so predictions degrade
gracefully rather than KeyError when no accelerator topology applies.

This module is deliberately jax-free (importable by offline tooling).
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Topology:
    """One hardware configuration the cost model can price.

    ``peak_tflops`` / ``hbm_gbps`` / ``ici_gbps`` are PER-CHIP peaks;
    ``chips`` is the data-parallel width the client axis shards over.
    ``ici_gbps=0`` means no interconnect (single chip / host) — the
    model then refuses to charge collective volume to ICI.
    """

    name: str
    chips: int
    peak_tflops: float      # bf16 MXU peak, TFLOP/s per chip
    hbm_gbps: float         # HBM bandwidth, GB/s (SI) per chip
    ici_gbps: float         # aggregate off-chip ICI, GB/s per chip
    usd_per_chip_hour: float


_TABLE = (
    # Dev/CI host: DDR-class bandwidth, priced free (self-hosted).
    Topology("cpu-host", 1, 1.0, 40.0, 0.0, 0.0),
    # v5e: 197 bf16 TFLOP/s, 819 GB/s HBM — the single-chip class this
    # repo's measured rounds come from (docs/PERFORMANCE.md micro-
    # benchmarks: 180 TF/s matmul, ~660 GB/s streaming peak observed).
    Topology("v5e-1", 1, 197.0, 819.0, 0.0, 1.20),
    Topology("v5e-8", 8, 197.0, 819.0, 200.0, 1.20),
    # v4: 275 bf16 TFLOP/s, 1228 GB/s HBM per chip.
    Topology("v4-8", 8, 275.0, 1228.0, 300.0, 3.22),
    Topology("v4-32", 32, 275.0, 1228.0, 300.0, 3.22),
    Topology("v4-128", 128, 275.0, 1228.0, 300.0, 3.22),
)

TOPOLOGIES: dict[str, Topology] = {t.name: t for t in _TABLE}


def get_topology(name: str) -> Topology:
    """Table lookup with an actionable error (the config knob
    ``cost_model_topology`` and bench's BENCH_COSTMODEL_TOPOLOGY both
    resolve through here)."""
    try:
        return TOPOLOGIES[name]
    except KeyError:
        raise ValueError(
            f"unknown topology {name!r}; known: "
            + ", ".join(sorted(TOPOLOGIES))
            + " (add entries in telemetry/topologies.py)"
        ) from None
