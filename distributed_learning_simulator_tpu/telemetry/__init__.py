"""Run telemetry: per-phase timing, recompile tracking, HBM watermarks.

The reference simulator has zero performance instrumentation (SURVEY §5),
and before this subsystem the reproduction measured cost as one opaque
``round_seconds`` wall-clock number. This package is the observability
layer every execution path (vmap simulator, threaded oracle, multihost
engine) reports through:

* :mod:`.phases` — per-round phase timers (client step / aggregate /
  eval / host-sync / post-round) around the existing ``annotate()``
  regions, with ``block_until_ready`` fencing only when
  ``telemetry_level='detailed'`` asks for it, so the default program is
  untouched.
* :mod:`.clock` + :mod:`.spans` — the distributed tracing layer:
  the ONE monotonic/wall clock convention every timing subsystem
  shares, and the per-host span recorder (``span_trace='on'``) that
  journals phase boundaries, DCN barrier waits (the cross-host skew
  signal), prefetch worker occupancy, and checkpoint barriers to
  ``spans_<host_id>.jsonl`` — doubling as a crash flight recorder;
  ``scripts/trace_timeline.py`` stitches all hosts' journals into a
  perfetto-loadable timeline (docs/OBSERVABILITY.md § Distributed
  tracing).
* :mod:`.recompile` — an XLA recompilation counter hooked on
  ``jax.monitoring`` compile events (names recovered from the
  ``jax_log_compiles`` log stream): any compile after the warmup round
  flags a shape-instability bug with the offending function name.
* :mod:`.memory` — the ONE ``memory_stats()`` probe (HBM watermark +
  capacity), replacing the ad-hoc call sites that used to be duplicated
  in simulator.py and scripts/measure_gtg_scale.py.
* :mod:`.client_stats` — trace-time-gated per-client training
  statistics computed INSIDE the compiled round (streaming reductions;
  no materialized per-client stack), a host-side median/MAD anomaly
  detector attributing which clients drove or corrupted a round, and
  the ``client_stats`` sub-object of the schema-v3 metrics record.
* :mod:`.costmodel` + :mod:`.topologies` — the predictive roofline
  cost model: the categorized traced-op ledger
  (utils/tracing.categorize_ops) evaluated against a checked-in
  topology table to predict per-round device time, per-category
  bottleneck attribution, and $/converged-run on pods the program has
  never touched — the ``costmodel`` sub-object of the schema-v6
  metrics record, the bench ``costmodel`` leg, and compare_bench's
  model-vs-measured drift gate (docs/OBSERVABILITY.md § Cost model).

Records land in ``metrics.jsonl`` through the schema-versioned builder in
``utils/reporting.py``; ``scripts/report_run.py`` renders an artifacts
dir offline. Levels, schema, and interpretation: docs/OBSERVABILITY.md.
"""

from distributed_learning_simulator_tpu.config import (
    CLIENT_STATS_LEVELS,
    TELEMETRY_LEVELS,
)
from distributed_learning_simulator_tpu.telemetry.client_stats import (
    PER_CLIENT_CAP,
    STAT_FIELDS,
    ClientStats,
    attribution_crosscheck,
    client_stats_record,
    detect_and_record,
    detect_anomalies,
)
from distributed_learning_simulator_tpu.telemetry.costmodel import (
    CONVERGED_RUN_ROUNDS,
    DEFAULT_ANCHOR,
    DEFAULT_EFFICIENCY,
    costmodel_record,
    ledger_totals,
    predict_round,
)
from distributed_learning_simulator_tpu.telemetry.memory import (
    device_memory_stats,
    hbm_limit_bytes,
    peak_hbm_bytes,
)
from distributed_learning_simulator_tpu.telemetry.phases import (
    NullPhaseTimer,
    PhaseTimer,
    make_phase_timer,
)
from distributed_learning_simulator_tpu.telemetry.recompile import (
    RecompileMonitor,
    log_round_compiles,
)
from distributed_learning_simulator_tpu.telemetry.spans import (
    SpanPhaseTimer,
    SpanRecorder,
    journal_filename,
)
from distributed_learning_simulator_tpu.telemetry.topologies import (
    TOPOLOGIES,
    Topology,
    get_topology,
)
from distributed_learning_simulator_tpu.telemetry.valuation import (
    ClientValuation,
    ValuationAuditor,
    ValuationState,
    cohort_crc,
    pearson_corr,
    spearman_corr,
    valuation_record,
)

__all__ = [
    "CLIENT_STATS_LEVELS",
    "CONVERGED_RUN_ROUNDS",
    "DEFAULT_ANCHOR",
    "DEFAULT_EFFICIENCY",
    "PER_CLIENT_CAP",
    "STAT_FIELDS",
    "TELEMETRY_LEVELS",
    "TOPOLOGIES",
    "ClientStats",
    "ClientValuation",
    "NullPhaseTimer",
    "PhaseTimer",
    "RecompileMonitor",
    "SpanPhaseTimer",
    "SpanRecorder",
    "Topology",
    "ValuationAuditor",
    "ValuationState",
    "attribution_crosscheck",
    "client_stats_record",
    "cohort_crc",
    "costmodel_record",
    "detect_and_record",
    "detect_anomalies",
    "device_memory_stats",
    "get_topology",
    "hbm_limit_bytes",
    "journal_filename",
    "ledger_totals",
    "log_round_compiles",
    "make_phase_timer",
    "peak_hbm_bytes",
    "pearson_corr",
    "predict_round",
    "spearman_corr",
    "valuation_record",
]
