"""Always-on client valuation: streaming Shapley-proxy telemetry.

The source paper's headline beyond-FedAvg capability is per-client
contribution scoring, but converged GTG at N=1000 costs 156-343 s/round
against the 2.3 s flagship round — ~100x too slow to run in-line, so
valuation was an offline batch job (ROADMAP item 4). This module turns it
into a per-round production signal with a measured fidelity bound:

* **Streaming estimator** — ``client_valuation='on'`` (off default: the
  exact pre-feature program, byte-identical v6 records — the PR 4/6/7
  trace-time off-gate discipline) adds ONE tiny per-cohort score vector
  to the jitted round, derived from the PR 4 client-stats matrix the
  round already computes: ``score_i = cos(update_i, aggregate) *
  ||update_i||``, normalized to unit L1 over the cohort. Host-side, each
  round's scores are scaled by the server loss-delta (previous test loss
  minus this round's — positive when the round helped) and folded into a
  per-client valuation vector with exponential decay
  (``valuation_decay``): clients whose updates consistently align with
  improving aggregates accumulate value; anti-aligned or inert clients
  decay toward zero. Cost: O(cohort) scalars per round on device and
  host — it rides the round at marginal cost, like scheduling.
* **Population scale** — the vector is a host numpy ``[N]`` array
  updated by cohort scatter; under ``client_residency='streamed'`` it
  attaches to the :class:`~..data.residency.HostShardStore` (the
  source of truth between dispatches), so a 1e6-client population costs
  4 MB of host RAM and O(cohort) work per round. Checkpointed in
  ``algo_state`` and restored on resume in both residency modes.
* **Fidelity audit** — on the sparse ``valuation_audit_every`` cadence,
  :class:`ValuationAuditor` re-materializes the CURRENT cohort's exact
  uploads (replaying local training from the round key — the PR 2/6/7
  round-key-chain discipline, algorithms/fedavg.py
  ``make_valuation_audit_fn``) and runs a truncated GTG walk over them
  (``algorithms/shapley.gtg_walk`` — the same estimator, cumsum prefix
  aggregation and all, budgeted by ``valuation_audit_permutations``),
  with a cross-round subset-utility memo keyed by the cohort hash
  (ROADMAP item 4b). The Spearman/Pearson correlation between the
  streaming vector and the audit SVs lands in the schema-v7
  ``valuation`` record sub-object — every run carries both the cheap
  always-on signal and a measured bound on how well it tracks exact
  Shapley. bench.py's ``valuation`` leg measures both the overhead and
  the small-N fidelity; scripts/compare_bench.py gates the correlation
  absolutely (``--valuation-corr-threshold``).

Semantics, cadence, and tuning: docs/OBSERVABILITY.md § Client
valuation; the incentive-side read of fault injection:
docs/ROBUSTNESS.md.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.telemetry.client_stats import (
    _IDX,
    PER_CLIENT_CAP,
)

#: Clients listed in the per-round record's top/bottom valuation tables.
TOP_K = 8


@dataclass(frozen=True)
class ClientValuation:
    """Static (trace-time) valuation configuration. ``from_config``
    returns None when ``client_valuation='off'`` — every call site gates
    on that, so off-mode runs compile the exact pre-feature program."""

    decay: float = 0.9
    audit_every: int = 0
    audit_permutations: int = 16

    @classmethod
    def from_config(cls, config) -> "ClientValuation | None":
        level = (
            getattr(config, "client_valuation", "off") or "off"
        ).lower()
        if level == "off":
            return None
        if level != "on":
            raise ValueError(
                f"unknown client_valuation {level!r}; known: off, on"
            )
        return cls(
            decay=float(getattr(config, "valuation_decay", 0.9)),
            audit_every=int(getattr(config, "valuation_audit_every", 0)),
            audit_permutations=int(
                getattr(config, "valuation_audit_permutations", 16)
            ),
        )

    def audit_round(self, round_idx: int) -> bool:
        """Whether this round runs the GTG audit walk. Round 0 never
        audits: the valuation vector is all-zero before its first fold,
        so a correlation against it is undefined."""
        return (
            self.audit_every > 0
            and round_idx > 0
            and round_idx % self.audit_every == 0
        )

    # ---- jit side ----------------------------------------------------------
    def scores(self, stats_matrix) -> jnp.ndarray:
        """Per-cohort streaming contribution scores from the ``[N, S]``
        client-stats matrix (telemetry/client_stats.py STAT_FIELDS):
        ``cos(update, aggregate) * ||update||`` normalized to unit L1.
        Non-finite entries (a corrupt upload's NaN norm) contribute 0 —
        a poisoned client must not poison the whole score vector."""
        cos = stats_matrix[:, _IDX["agg_cosine"]]
        norm = stats_matrix[:, _IDX["update_norm"]]
        raw = cos * norm
        raw = jnp.where(jnp.isfinite(raw), raw, 0.0)
        return raw / (jnp.sum(jnp.abs(raw)) + 1e-12)


def cohort_crc(ids, n_clients: int) -> int:
    """Cohort fingerprint keying the cross-round audit memo — the same
    int64-CRC formula as the metrics record's ``cohort_hash``
    (simulator.emit_record), with the full population spelled out when
    sampling is off (``ids=None``)."""
    arr = (
        np.arange(n_clients, dtype=np.int64) if ids is None
        else np.ascontiguousarray(ids, dtype=np.int64)
    )
    return zlib.crc32(arr.tobytes())


# ---- correlations (jax-free; unit-testable without a backend) --------------


def pearson_corr(a, b) -> float | None:
    """Pearson correlation over finite pairs; None when degenerate
    (fewer than 2 finite pairs, or either side has zero variance)."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    ok = np.isfinite(a) & np.isfinite(b)
    if ok.sum() < 2:
        return None
    a, b = a[ok], b[ok]
    if np.ptp(a) == 0.0 or np.ptp(b) == 0.0:
        return None
    return float(np.corrcoef(a, b)[0, 1])


def _average_ranks(x: np.ndarray) -> np.ndarray:
    """Average-rank transform (ties share the mean of their positions) —
    un-updated clients all sitting at exactly 0 must not get an
    arbitrary tie-broken ordering."""
    order = np.argsort(x, kind="mergesort")
    ranks = np.empty(x.shape[0], dtype=np.float64)
    sx = x[order]
    i = 0
    while i < x.shape[0]:
        j = i
        while j + 1 < x.shape[0] and sx[j + 1] == sx[i]:
            j += 1
        ranks[order[i : j + 1]] = 0.5 * (i + j)
        i = j + 1
    return ranks


def spearman_corr(a, b) -> float | None:
    """Spearman rank correlation (average ranks on ties) over finite
    pairs; None when degenerate. The fidelity gate's metric: valuation
    is a RANKING signal (who contributed more), so rank correlation is
    the honest bound — scale disagreement between loss-delta units and
    accuracy-utility SVs is irrelevant."""
    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    ok = np.isfinite(a) & np.isfinite(b)
    if ok.sum() < 2:
        return None
    ra, rb = _average_ranks(a[ok]), _average_ranks(b[ok])
    if np.ptp(ra) == 0.0 or np.ptp(rb) == 0.0:
        return None
    return float(np.corrcoef(ra, rb)[0, 1])


# ---- host-side state --------------------------------------------------------


class ValuationState:
    """The persistent per-client valuation vector (host numpy ``[N]``).

    Under ``client_residency='streamed'`` the vector attaches to the
    :class:`HostShardStore` (``store.valuation``) so the store remains
    the one source of truth the streamed checkpoints and scripts read;
    resident runs own the array directly. Either way updates are an
    O(cohort) scatter."""

    def __init__(self, n_clients: int, store=None):
        self._store = store
        if store is not None:
            if getattr(store, "valuation", None) is None:
                store.attach_valuation(
                    np.zeros(n_clients, dtype=np.float64)
                )
            if store.valuation.shape[0] != n_clients:
                raise ValueError(
                    "store valuation length "
                    f"{store.valuation.shape[0]} != n_clients {n_clients}"
                )
        else:
            self._values = np.zeros(n_clients, dtype=np.float64)

    @property
    def values(self) -> np.ndarray:
        return (
            self._store.valuation if self._store is not None
            else self._values
        )

    def load(self, values) -> None:
        """Restore from a checkpoint's saved vector (resume path)."""
        values = np.asarray(values, dtype=np.float64)
        if values.shape != self.values.shape:
            raise ValueError(
                "checkpoint valuation vector has "
                f"{values.shape[0]} clients, this run has "
                f"{self.values.shape[0]}; resume with the configuration "
                "the checkpoint was written with"
            )
        if self._store is not None:
            self._store.attach_valuation(values)
        else:
            self._values = values

    def fold(self, ids, scores, loss_delta: float, decay: float) -> None:
        """One round's exponential-decay fold: participants' entries move
        toward ``loss_delta * score``; non-participants keep their value
        (their evidence didn't change). ``ids=None`` = whole population.
        """
        contrib = loss_delta * np.asarray(scores, dtype=np.float64)
        contrib = np.where(np.isfinite(contrib), contrib, 0.0)
        v = self.values
        if ids is None:
            v *= decay
            v += (1.0 - decay) * contrib
        else:
            idx = np.asarray(ids, dtype=np.int64)
            v[idx] = decay * v[idx] + (1.0 - decay) * contrib

    def top(self, k: int = TOP_K) -> list[tuple[int, float]]:
        v = self.values
        order = np.argsort(-v, kind="mergesort")[: min(k, v.shape[0])]
        return [(int(i), float(v[i])) for i in order]

    def bottom(self, k: int = TOP_K) -> list[tuple[int, float]]:
        v = self.values
        order = np.argsort(v, kind="mergesort")[: min(k, v.shape[0])]
        return [(int(i), float(v[i])) for i in order]

    def summary(self, last_audit: dict | None = None) -> dict:
        """The result-dict face of the vector (bench.py's valuation leg
        and library callers): top/bottom tables + the latest audit."""
        return {
            "top_clients": [
                {"id": i, "value": round(v, 8)} for i, v in self.top()
            ],
            "bottom_clients": [
                {"id": i, "value": round(v, 8)} for i, v in self.bottom()
            ],
            "last_audit": last_audit,
        }


def valuation_record(state: ValuationState, ids, loss_delta: float,
                     audit: dict | None = None,
                     per_client_cap: int = PER_CLIENT_CAP) -> dict:
    """Build the ``valuation`` sub-object of a schema-v7 metrics record
    (utils/reporting.build_round_record attaches it): top-k/bottom-k
    client tables always; raw per-client values only for populations up
    to ``per_client_cap`` (the client-stats rule — large-N runs must not
    bloat metrics.jsonl); the audit result on audit rounds."""
    v = state.values
    n = int(v.shape[0])
    record: dict = {
        "n_clients": n,
        "updated": n if ids is None else int(np.asarray(ids).shape[0]),
        "loss_delta": round(float(loss_delta), 6),
        "top_clients": [
            {"id": i, "value": round(val, 8)} for i, val in state.top()
        ],
        "bottom_clients": [
            {"id": i, "value": round(val, 8)} for i, val in state.bottom()
        ],
    }
    if n <= per_client_cap:
        record["per_client"] = {
            "client_ids": list(range(n)),
            "value": [round(float(x), 8) for x in v],
        }
    if audit is not None:
        record["audit"] = audit
    return record


def grade_client_labels(y, num_classes: int, seed: int = 0) -> np.ndarray:
    """Graded label corruption for the fidelity differential config.

    Client ``i`` of ``N`` gets fraction ``i / (N - 1)`` of its packed
    labels replaced with uniform-random classes: a monotonic
    data-quality gradient from clean (client 0) to noise (client N-1),
    so BOTH a faithful contribution estimator and exact Shapley should
    rank clients near-monotonically — the engineered ground truth the
    bench fidelity leg and tests/test_valuation.py correlate against.
    Shared by both so they measure the same workload. ``y`` is the
    packed ``[N, S]`` label array (data/partition.ClientData.y).
    """
    y = np.array(y, copy=True)
    n = y.shape[0]
    rng = np.random.default_rng(seed)
    for i in range(n):
        frac = i / max(n - 1, 1)
        k = int(round(frac * y.shape[1]))
        if k == 0:
            continue
        slots = rng.choice(y.shape[1], size=k, replace=False)
        y[i, slots] = rng.integers(0, num_classes, size=k)
    return y


class ValuationAuditor:
    """Sparse-cadence GTG cross-validation of the streaming estimator.

    On ``valuation_audit_every`` rounds the auditor (1) re-materializes
    the round's exact cohort uploads by replaying local training from
    the round key (``FedAvg.make_valuation_audit_fn`` — faults/async/
    persistent state are refused by config.validate(), which is what
    keeps the replay exact), (2) runs a budgeted GTG permutation walk
    over the stack (``algorithms/shapley.gtg_walk`` — the identical
    estimator the offline GTG server runs, down to the cumsum prefix
    walker), optionally seeding its subset-utility memo from the last
    audit of the same cohort (``cohort_crc``; only when
    ``config.gtg_cross_round_memo`` opts in — see the staleness note at
    the seeding site), and (3) reports Spearman/Pearson correlation
    between the current streaming valuation vector (restricted to the
    cohort) and the audits' cumulative per-client SV estimate. The
    audit NEVER feeds back into training — it is a pure read; the
    round's aggregate came from the normal program.

    Cost: one extra cohort training pass plus
    ``min(valuation_audit_permutations, N)`` permutation walks — the
    "full walks on a sparse cadence" half of ROADMAP item 4's plan, with
    the streaming vector as the always-on other half.
    """

    def __init__(self, config, cv: ClientValuation, algorithm, apply_fn,
                 optimizer, preprocess, eval_fn, client_data,
                 eval_batches, n_clients: int):
        self._config = config
        self._cv = cv
        self._stack_jit = jax.jit(
            algorithm.make_valuation_audit_fn(
                apply_fn, optimizer, preprocess=preprocess
            )
        )
        self._eval_fn = eval_fn
        # Host copies of the packed shards: cohort gathers for the replay
        # work identically under resident and streamed residency (the
        # arrays are the same ones the store/device copies came from).
        self._x = np.asarray(client_data.x)
        self._y = np.asarray(client_data.y)
        self._mask = np.asarray(client_data.mask)
        self._sizes = np.asarray(client_data.sizes)
        self._eval_batches = eval_batches
        self._n = n_clients
        self._evaluator = None
        self._capped_batches = None
        # Cross-round memo: only the LATEST walk's utilities are kept
        # (the reuse premise is consecutive same-cohort walks; under
        # sampling the key changes every audit and an unbounded
        # per-cohort dict would just leak). {cohort crc -> utilities}.
        self._memo_store: dict[int, dict] = {}
        # Running per-CLIENT mean of audit SVs, keyed by TRUE client id:
        # a single round's GTG SVs are Monte-Carlo + accuracy-
        # quantization noisy (marginals live in units of 1/n_test); the
        # streaming vector is multi-round evidence, so the honest
        # fidelity reference is the audits' cumulative estimate — the
        # same round-averaging multi-round Shapley does. Population-
        # indexed (not per-cohort-keyed) so sampled cohorts accumulate
        # too, in O(N) memory.
        self._sv_sum = np.zeros(n_clients, dtype=np.float64)
        self._sv_count = np.zeros(n_clients, dtype=np.int64)
        self._n_audits = 0
        # Decoupled from every training stream: the audit's permutation
        # draws must not perturb (or be perturbed by) the run's RNG.
        self._rng = np.random.default_rng(
            getattr(config, "seed", 0) + 29
        )

    def due(self, round_idx: int) -> bool:
        return self._cv.audit_round(round_idx)

    def _get_evaluator(self):
        if self._evaluator is None:
            from distributed_learning_simulator_tpu.algorithms.shapley import (
                _EVAL_CHUNK,
                _SubsetEvaluator,
                cap_eval_batches,
                eval_mesh_devices,
            )

            # f32 stack reads: the audit is the fidelity REFERENCE, so it
            # takes the exact-parity dtype (an explicit
            # shapley_eval_dtype='bfloat16' wins, for large-N audits
            # where the stack-read traffic matters).
            dtype = getattr(self._config, "shapley_eval_dtype", "auto")
            self._evaluator = _SubsetEvaluator(
                self._eval_fn,
                chunk=getattr(
                    self._config, "shapley_eval_chunk", _EVAL_CHUNK
                ),
                eval_dtype="float32" if dtype == "auto" else dtype,
                # Budgeted audits at production cadence ride the SAME
                # mesh as the run (single-host mesh_devices sharding of
                # the walk's subset/group axis — bit-identical to the
                # serial walk; multihost keeps the serial evaluator).
                mesh_devices=eval_mesh_devices(self._config),
            )
            self._capped_batches = cap_eval_batches(
                self._eval_batches,
                getattr(self._config, "shapley_eval_samples", None),
            )
        return self._evaluator

    def run(self, round_idx: int, round_key, prev_global, ids,
            values: np.ndarray, lr_scale: float = 1.0) -> dict:
        """One audit: returns the ``audit`` sub-object (correlations,
        walk budget spent, memo reuse, wall seconds)."""
        from distributed_learning_simulator_tpu.algorithms.fedavg import (
            round_key_splits,
        )
        from distributed_learning_simulator_tpu.algorithms.shapley import (
            SubsetMemo,
            eval_subsets,
            gtg_walk,
        )

        t0 = time.perf_counter()
        idx = (
            np.arange(self._n, dtype=np.int64) if ids is None
            else np.asarray(ids, dtype=np.int64)
        )
        n = int(idx.shape[0])
        # The round's split chain (audits refuse failure models, so the
        # 4-way split): train_key fans out to the exact per-client keys
        # the live round used; payload_key replays fed_quant's upload
        # quantization.
        _, train_key, payload_key, _, _ = round_key_splits(
            round_key, with_faults=False
        )
        client_keys = jax.random.split(train_key, n)
        stack = self._stack_jit(
            prev_global,
            jnp.asarray(self._x[idx]),
            jnp.asarray(self._y[idx]),
            jnp.asarray(self._mask[idx]),
            client_keys,
            payload_key,
            jnp.float32(lr_scale),
        )
        evaluator = self._get_evaluator()
        stack = evaluator.prepare_stack(stack)
        sizes_k = jnp.asarray(self._sizes[idx])
        key = cohort_crc(idx, self._n)
        # Cross-round memo reuse follows the same opt-in as the GTG
        # server (config.gtg_cross_round_memo, default off): reused
        # utilities describe an EARLIER audit's params, and at a sparse
        # audit cadence the model moves a lot between audits — measured:
        # a 0.99 hit rate dragged the per-round audit spearman from 0.88
        # to 0.43 on the graded-quality differential. Off keeps every
        # audit's utilities fresh (the honest default); on trades
        # fidelity for walk cost, with memo_hit_rate + the correlation
        # itself as the self-policing record.
        cross_round = bool(
            getattr(self._config, "gtg_cross_round_memo", False)
        )
        seed = self._memo_store.get(key) if cross_round else None
        if seed:
            # Same rule as GTGShapley's cross-round memo: the empty and
            # grand coalitions anchor the walk — always fresh.
            seed = {k: v for k, v in seed.items() if 0 < len(k) < n}
        memo = SubsetMemo(seed)
        grand = frozenset(range(n))
        eval_subsets(
            evaluator, stack, sizes_k, prev_global,
            self._capped_batches, n, memo, [frozenset(), grand],
        )
        cfg = self._config
        sv_arr, n_perms, converged = gtg_walk(
            evaluator, stack, sizes_k, prev_global, self._capped_batches,
            n, self._rng,
            eps=getattr(cfg, "gtg_eps", 1e-3),
            cap=self._cv.audit_permutations,
            last_k=getattr(cfg, "gtg_last_k", 10),
            converge_criteria=getattr(cfg, "gtg_converge_criteria", 0.05),
            # Self-consistent truncation reference: the grand-coalition
            # utility from the SAME (possibly subsampled) estimator, the
            # rule GTGShapley applies whenever estimators could disagree.
            trunc_ref=memo[grand],
            prefix_mode=getattr(cfg, "gtg_prefix_mode", "cumsum"),
            memo=memo,
            starts_per_iteration=min(self._cv.audit_permutations, n),
        )
        # Release the evaluator's per-round placement cache: in mesh mode
        # it pins this audit's replicated stack copy until the next audit
        # otherwise (algorithms/shapley._SubsetEvaluator.release_round).
        evaluator.release_round()
        if cross_round:
            # Latest-walk-only retention: consecutive audits of the same
            # cohort reuse it; a changed cohort simply misses.
            self._memo_store = {key: dict(memo)}
        self._sv_sum[idx] += sv_arr
        self._sv_count[idx] += 1
        self._n_audits += 1
        sv_mean = self._sv_sum[idx] / np.maximum(self._sv_count[idx], 1)
        vals_cohort = np.asarray(values, dtype=np.float64)[idx]
        # The reported correlations compare the streaming vector against
        # the CUMULATIVE audit SV estimate (see _sv_accum) — the
        # per-round walk's own SVs additionally land as spearman_round
        # so single-audit noise stays inspectable.
        sp = spearman_corr(vals_cohort, sv_mean)
        pe = pearson_corr(vals_cohort, sv_mean)
        sp_round = spearman_corr(vals_cohort, sv_arr)
        hit_rate = memo.hit_rate() if cross_round else None
        return {
            "spearman": None if sp is None else round(sp, 4),
            "pearson": None if pe is None else round(pe, 4),
            "spearman_round": (
                None if sp_round is None else round(sp_round, 4)
            ),
            "audits": int(self._n_audits),
            "permutations": int(n_perms),
            "subset_evals": int(memo.evaluated),
            "converged": bool(converged),
            "memo_hit_rate": (
                None if hit_rate is None else round(hit_rate, 4)
            ),
            # Walk sharding (algorithms/shapley.eval_mesh_devices): how
            # many devices this audit's subset evaluation partitioned
            # over — present ONLY when the walk actually sharded, so
            # serial-audit configs keep their pre-PR-14 audit records
            # byte-identical (the same no-opt-in-no-layout-change rule
            # as the v10 gtg sub-object). Rendered with the wall-clock
            # by report_run's valuation section.
            **(
                {"devices": int(evaluator.devices)}
                if evaluator.devices > 1 else {}
            ),
            "seconds": round(time.perf_counter() - t0, 3),
        }
