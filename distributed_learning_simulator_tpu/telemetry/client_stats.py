"""Per-client training statistics, computed INSIDE the jitted round.

PR 3 made the *runtime* observable (phase timings, recompiles, HBM
watermarks) but the training dynamics stayed a black box: a round
reported one accuracy number and nothing about which of the N clients
drove it, diverged, or was corrupted — even though the failure injector
(robustness/faults.py) can corrupt clients that no subsystem could
detect or attribute. Reference simulators treat per-client metrics as a
first-class output (FedJAX's per-client evaluation stream, FL_PyTorch's
per-client optimization statistics); at hardware speed they must be
computed inside the compiled round — no host syncs, no materialized
per-client parameter stacks.

Design, mirroring :mod:`robustness.faults`:

* :class:`ClientStats` is built from config (``client_stats='off'``
  returns None, and every call site gates at TRACE time on that — the
  default compiles the exact pre-feature program, same RNG streams,
  same HLO).
* Per client the round program computes a compact f32 stats vector
  (:data:`STAT_FIELDS`): local loss before/after the local run, the L2
  norm of the uploaded update, the mean per-step gradient norm, the
  cosine of the client's update against the aggregate update, and the
  count of non-finite uploaded elements. All of it comes from STREAMING
  per-chunk reductions — O(1) scalars plus a strided
  ``client_stats_probe``-coordinate delta probe per client — so the
  fused and bucketed aggregation paths never materialize the
  ``[n_clients, n_params]`` stack. Stats are stacked ``[N, S]`` on
  device and fetched once per ``client_stats_every`` rounds inside the
  round's single metric ``device_get``, preserving async dispatch.
* The cosine uses the probe coordinates (exact when the model has at
  most ``client_stats_probe`` parameters); norms and counts are exact
  full reductions.
* Host-side, :func:`detect_anomalies` is a median/MAD outlier detector:
  robust z-scores flag anomalous clients per round with a reason
  (``non_finite`` catches ``corrupt_nan`` uploads; a high-side
  ``update_norm`` z-score catches ``corrupt_scale``; a high-side
  ``loss_after`` z-score catches genuinely diverging clients). High-side
  only: a zero-size update (an empty Dirichlet shard) is not an anomaly.
  The MAD rules assume an honest majority — with more than half the
  cohort corrupt the median itself is poisoned, the same assumption
  every robust aggregation rule makes.
* :func:`client_stats_record` builds the ``client_stats`` sub-object of
  the schema-v3 metrics record (quantile summaries always; raw
  per-client values only for cohorts of at most :data:`PER_CLIENT_CAP`
  clients, so large-N runs don't bloat metrics.jsonl), shared by the
  vmap simulator and the threaded oracle.

Levels, layout, cadence, and detector tuning: docs/OBSERVABILITY.md;
the detection side of fault injection: docs/ROBUSTNESS.md.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.config import CLIENT_STATS_LEVELS

#: Column order of the per-client ``[N, S]`` stats matrix. Fields an
#: execution path cannot produce (the threaded oracle's workers report
#: no losses) are NaN and render as null in the record.
STAT_FIELDS = (
    "loss_before",      # first-step local loss (global params, 1st batch)
    "loss_after",       # final-epoch mean local loss
    "update_norm",      # L2 norm of the uploaded delta (post-corruption)
    "grad_norm",        # sqrt(mean per-step squared gradient L2 norm)
    "agg_cosine",       # cos(client delta, aggregate delta) over the probe
    "nonfinite_count",  # non-finite elements in the upload (exact count)
)

_IDX = {name: i for i, name in enumerate(STAT_FIELDS)}

#: Cohorts up to this size get raw per-client values in the record
#: (report_run's per-client loss sparklines); larger cohorts get
#: quantile summaries only.
PER_CLIENT_CAP = 32

#: Quantiles summarizing each stat column in the record.
_QUANTILES = (0, 25, 50, 75, 100)


@dataclass(frozen=True)
class ClientStats:
    """Static (trace-time) client-statistics configuration; the per-round
    reductions are pure functions of round state, so one compiled round
    program serves every round."""

    every: int = 1
    probe: int = 4096
    mad_threshold: float = 8.0

    @classmethod
    def from_config(cls, config) -> "ClientStats | None":
        """None when ``client_stats='off'`` — callers gate every
        trace-time branch on that, so off-mode runs compile the exact
        pre-feature program."""
        level = (getattr(config, "client_stats", "off") or "off").lower()
        if level == "off":
            return None
        if level not in CLIENT_STATS_LEVELS:
            raise ValueError(
                f"unknown client_stats {level!r}; known: "
                + ", ".join(CLIENT_STATS_LEVELS)
            )
        return cls(
            every=int(getattr(config, "client_stats_every", 1)),
            probe=int(getattr(config, "client_stats_probe", 4096)),
            mad_threshold=float(
                getattr(config, "client_stats_mad_threshold", 8.0)
            ),
        )

    def fetch_round(self, round_idx: int) -> bool:
        """Whether this round's stats are fetched to host (the device
        computes them every round; only the device->host transfer is on
        the ``client_stats_every`` cadence)."""
        return round_idx % self.every == 0

    # ---- jit-side streaming reductions ------------------------------------
    def _stride(self, tree) -> int:
        total = sum(
            leaf.size for leaf in jax.tree_util.tree_leaves(tree)
        )
        return max(1, total // max(self.probe, 1))

    def probe_delta(self, base_tree, new_tree):
        """``[K]`` strided probe of ``new - base`` (one model). The SAME
        stride/leaf-order as :meth:`add_upload_stats` samples, so client
        probes and the aggregate probe cover identical coordinates."""
        stride = self._stride(base_tree)
        rows = [
            (n.astype(jnp.float32) - b.astype(jnp.float32)).reshape(-1)[
                ::stride
            ]
            for b, n in zip(
                jax.tree_util.tree_leaves(base_tree),
                jax.tree_util.tree_leaves(new_tree),
            )
        ]
        return jnp.concatenate(rows)

    def add_upload_stats(self, train_metrics: dict, global_params,
                         stacked) -> dict:
        """Fold per-client upload statistics into the train-metrics dict
        (leading axis of every ``stacked`` leaf = clients). Called once
        per chunk on the fused/bucketed paths — the per-client outputs
        are O(1) scalars plus the ``[chunk, K]`` probe, never the stack
        — and once on the full stack on the materializing path. Applied
        AFTER fault corruption: the stats describe what the server
        received."""
        stride = self._stride(global_params)
        sq = 0.0
        nonfinite = 0.0
        probes = []
        for g, c in zip(
            jax.tree_util.tree_leaves(global_params),
            jax.tree_util.tree_leaves(stacked),
        ):
            d = c.astype(jnp.float32) - g.astype(jnp.float32)
            flat = d.reshape((d.shape[0], -1))
            sq = sq + jnp.sum(flat * flat, axis=1)
            nonfinite = nonfinite + jnp.sum(
                (~jnp.isfinite(c.reshape((c.shape[0], -1)))).astype(
                    jnp.float32
                ),
                axis=1,
            )
            probes.append(flat[:, ::stride])
        out = dict(train_metrics)
        out["update_sq"] = sq
        out["nonfinite_count"] = nonfinite
        out["stat_probe"] = jnp.concatenate(probes, axis=1)
        return out

    def stats_matrix(self, train_metrics: dict, agg_probe) -> jnp.ndarray:
        """Assemble the ``[N, S]`` stats matrix (:data:`STAT_FIELDS`
        column order) from the collected per-client metrics and the
        aggregate-delta probe. Missing loss/grad columns (an execution
        path that cannot produce them) fill with NaN."""
        probe = train_metrics["stat_probe"]
        n = probe.shape[0]
        nan = jnp.full((n,), jnp.nan, jnp.float32)
        dots = probe @ agg_probe
        denom = (
            jnp.linalg.norm(probe, axis=1) * jnp.linalg.norm(agg_probe)
            + 1e-12
        )
        grad_sq = train_metrics.get("grad_sq_mean")
        cols = (
            train_metrics.get("loss_first", nan),
            train_metrics.get("loss", nan),
            jnp.sqrt(train_metrics["update_sq"]),
            nan if grad_sq is None else jnp.sqrt(grad_sq),
            dots / denom,
            train_metrics["nonfinite_count"],
        )
        return jnp.stack(
            [c.astype(jnp.float32) for c in cols], axis=1
        )

    def stack_stats(self, prev_global, stacked, aggregated) -> jnp.ndarray:
        """One-shot ``[N, S]`` stats from a materialized upload stack and
        the raw aggregate (the threaded oracle's path: it holds the stack
        at the rendezvous barrier but its workers report no losses)."""
        tm = self.add_upload_stats({}, prev_global, stacked)
        return self.stats_matrix(tm, self.probe_delta(prev_global, aggregated))


# ---- host-side detection + record building --------------------------------


def detect_anomalies(stats: np.ndarray, mad_threshold: float = 8.0):
    """Median/MAD outlier detection over one round's ``[N, S]`` stats.

    Returns ``(flagged, reasons)``: a sorted list of flagged row indices
    and ``{row: reason}`` ("+"-joined when several rules fire). Rules:

    * ``non_finite`` — any non-finite uploaded element (catches
      ``corrupt_nan`` regardless of how many clients are corrupt);
    * ``update_norm`` / ``loss_diverged`` — robust z-score
      ``(x - median) / (1.4826 * MAD)`` above ``mad_threshold``,
      HIGH side only (a small update is an empty shard, not an attack).
      Computed over ACTIVE clients only — rows with ``update_norm == 0``
      never trained (empty Dirichlet shards, whose all-zero stats rows
      the bucketed path emits by design) and are excluded from both the
      median/MAD population and the flaggable set, so a mostly-empty
      cohort cannot collapse the median to 0 and mark every honest
      client an outlier. Needs at least 3 active finite values; with
      MAD 0 (identical updates) the denominator floors at
      ``1e-6 * |median|`` so float jitter never flags, while a
      100x-scaled upload still scores astronomically.

    The z rules assume an honest majority — the same assumption the
    robust aggregation rules make. Pure numpy (no jax import cost in the
    hot loop; unit-testable without a backend).
    """
    stats = np.asarray(stats, dtype=np.float64)
    n = stats.shape[0]
    reasons: dict[int, list[str]] = {}

    def flag(i: int, reason: str) -> None:
        reasons.setdefault(int(i), []).append(reason)

    nonfinite = np.nan_to_num(stats[:, _IDX["nonfinite_count"]], nan=1.0)
    for i in np.flatnonzero(nonfinite > 0):
        flag(i, "non_finite")
    # Active = actually uploaded something: zero-norm rows are empty
    # shards (the bucketed path's skipped clients keep all-zero rows),
    # excluded from the z population AND from flagging so they can
    # neither be outliers nor drag the median to 0.
    upd = stats[:, _IDX["update_norm"]]
    active = np.isfinite(upd) & (upd > 0.0)
    if n >= 3:
        for col, reason in (
            ("update_norm", "update_norm"),
            ("loss_after", "loss_diverged"),
        ):
            x = stats[:, _IDX[col]]
            ok = active & np.isfinite(x)
            if ok.sum() < 3:
                continue
            med = float(np.median(x[ok]))
            mad = float(np.median(np.abs(x[ok] - med)))
            denom = max(1.4826 * mad, 1e-6 * abs(med), 1e-12)
            z = (x - med) / denom
            for i in np.flatnonzero(ok & (z > mad_threshold)):
                flag(i, reason)
    flagged = sorted(reasons)
    return flagged, {i: "+".join(r) for i, r in reasons.items()}


def _san(v) -> float | None:
    """JSON-safe scalar: non-finite floats become None (metrics.jsonl
    must stay strict JSON — NaN is not)."""
    v = float(v)
    return v if np.isfinite(v) else None


def client_stats_record(stats: np.ndarray, flagged, reasons,
                        participants=None, extras: dict | None = None,
                        per_client_cap: int = PER_CLIENT_CAP) -> dict:
    """Build the ``client_stats`` sub-object of a schema-v3 metrics
    record — the ONE shape both execution paths emit
    (utils/reporting.build_round_record attaches it).

    ``participants`` (optional ``[N]`` int array) maps stats rows to true
    client ids under participation sampling. ``extras`` merges
    algorithm-specific round scalars (fed_quant's ``quant_mse``,
    sign_SGD's ``vote_agreement``).
    """
    stats = np.asarray(stats, dtype=np.float64)
    n = stats.shape[0]
    ids = (
        np.arange(n, dtype=np.int64) if participants is None
        else np.asarray(participants, dtype=np.int64)
    )
    quantiles = {}
    for name, col in _IDX.items():
        x = stats[:, col]
        finite = x[np.isfinite(x)]
        quantiles[name] = {
            f"p{q}": (
                round(float(np.percentile(finite, q)), 6)
                if finite.size else None
            )
            for q in _QUANTILES
        }
    record: dict = {
        "n_clients": n,
        "flagged_clients": [int(ids[i]) for i in flagged],
        "flag_reason": {str(int(ids[i])): reasons[i] for i in flagged},
        "quantiles": quantiles,
    }
    if n <= per_client_cap:
        per_client: dict = {"client_ids": [int(i) for i in ids]}
        for name, col in _IDX.items():
            per_client[name] = [
                round(float(x), 6) if np.isfinite(x) else None
                for x in stats[:, col]
            ]
        record["per_client"] = per_client
    if extras:
        record.update({k: _san(v) for k, v in extras.items()})
    return record


def detect_and_record(stats, cs: "ClientStats", round_idx: int,
                      logger=None, participants=None,
                      extras: dict | None = None):
    """One round's host-side flagging pipeline — detector, record
    builder, WARNING log — shared verbatim by the vmap simulator and the
    threaded oracle so the two paths cannot drift. Returns
    ``(record, n_flagged)``."""
    stats = np.asarray(stats)
    flagged, reasons = detect_anomalies(stats, cs.mad_threshold)
    record = client_stats_record(
        stats, flagged, reasons, participants=participants, extras=extras
    )
    if flagged and logger is not None:
        logger.warning(
            "round %d: client-stats detector flagged clients %s (%s)",
            round_idx, record["flagged_clients"], record["flag_reason"],
        )
    return record, len(flagged)


def attribution_crosscheck(shapley_values: np.ndarray,
                           stats: np.ndarray) -> float | None:
    """Cross-check Shapley utility attribution against the in-round
    statistics: Pearson correlation between per-client Shapley value and
    local loss improvement (``loss_before - loss_after``). A strongly
    negative value says the expensive attribution and the cheap
    per-client signal disagree — worth a look either way. None when
    either side is degenerate (too few finite pairs, zero variance)."""
    sv = np.asarray(shapley_values, dtype=np.float64)
    stats = np.asarray(stats, dtype=np.float64)
    improve = stats[:, _IDX["loss_before"]] - stats[:, _IDX["loss_after"]]
    ok = np.isfinite(sv) & np.isfinite(improve)
    if ok.sum() < 2:
        return None
    sv, improve = sv[ok], improve[ok]
    if np.ptp(sv) == 0.0 or np.ptp(improve) == 0.0:
        return None
    return float(np.corrcoef(sv, improve)[0, 1])
