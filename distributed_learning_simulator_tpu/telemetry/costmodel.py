"""Predictive TPU cost model: roofline analysis of the traced-op ledger.

The bench proxy (utils/tracing.parse_device_trace) already captures a
bit-identical per-round op stream — 136.4 GB for the cnn headline,
1249.0 GB for the flagship ResNet program (BENCH_r05). This module turns
that change DETECTOR into a PREDICTOR (ROADMAP item 5, SCALE-Sim-style):
evaluate the categorized ledger (utils/tracing.categorize_ops) against
the checked-in topology table (telemetry/topologies.py) to predict
per-round device time, attribute the bottleneck per op category
(compute- vs memory- vs collective-bound), and price a converged run in
chip-hours/USD on hardware the program has never touched.

Model, per category ``c`` on topology ``T`` with ``n`` chips:

    t_c = max( flops_c / (n * peak_flops * E_mxu),
               bytes_c / (n * hbm_bw    * E_hbm) )      [roofline]
    t_collective = collective_bytes / n / (ici_bw * E_ici)
    predicted_round = sum_c t_c + t_collective (+ all-reduce estimate)

The division by ``n`` encodes this repo's scaling mode: the client axis
shards data-parallel across the mesh (parallel/mesh.py), so per-chip
byte/FLOP volume divides while the global-model all-reduce (estimated
as ``2 * param_bytes * (n-1)/n`` when ``param_bytes`` is given — the
traced single-chip ledger contains no collectives) rides the ICI term.
This is an OPTIMISTIC linear-scaling bound at small per-chip cohorts;
the fitted error band in docs/PERFORMANCE.md § Predicted pod-scale cost
is the honest calibration record.

``DEFAULT_EFFICIENCY`` holds the fitted fractions of datasheet peak the
measured programs actually reach (fit procedure + residuals documented
in docs/PERFORMANCE.md). The model predicts DEVICE time; the cnn
headline's wall-clock carries a ~28% host-side share on top
(docs/PERFORMANCE.md § Round batching), which is exactly the
systematic under-prediction the drift gate's band must cover
(scripts/compare_bench.py --model-drift-threshold).

Deliberately jax-free: the ledger is a plain dict, so
scripts/compare_bench.py-style offline tooling and the tier-1 tests
(tests/test_costmodel.py) evaluate the model without touching a device.
"""

from __future__ import annotations

from distributed_learning_simulator_tpu.telemetry.topologies import (
    TOPOLOGIES,
    Topology,
    get_topology,
)

GIB = 2**30

# Fitted fractions of datasheet peak (docs/PERFORMANCE.md § Predicted
# pod-scale cost). "hbm" is fitted on the flagship program (the robust
# ±0.2% wall-clock signal): 1249.0 GiB / 2.2754 s measured = 589 GB/s
# effective on a v5e-class chip = 0.72 of the 819 GB/s datasheet peak.
# "mxu" reflects the measured in-context fusion rate (~95 TF/s mega-
# fusions / 197 peak ~ 0.5; the isolated 8192^3 matmul reaches 0.91).
# "ici" is a nominal large-message collective efficiency; no traced
# collective volume exists yet to fit it (single-chip traces), so it is
# a documented placeholder until a multi-chip trace lands.
DEFAULT_EFFICIENCY = {"mxu": 0.50, "hbm": 0.72, "ici": 0.70}

# The topology the repo's measured rounds come from (the anchor row the
# model is validated against): a v5e-class single chip
# (docs/PERFORMANCE.md microbenchmarks).
DEFAULT_ANCHOR = "v5e-1"

# The documented converged-run horizon (150-round flagship trajectories,
# docs/PERFORMANCE.md § Converged flagship runs): the default rounds
# count behind "$/converged-run" projections.
CONVERGED_RUN_ROUNDS = 150


def ledger_totals(ledger: dict) -> dict:
    """Summed ``{"bytes_gb", "flops_g", "device_ms", "op_count"}`` over a
    categorized ledger (zeros for an empty one)."""
    out = {"bytes_gb": 0.0, "flops_g": 0.0, "device_ms": 0.0, "op_count": 0}
    for entry in ledger.values():
        for key in out:
            out[key] += entry.get(key, 0)
    return out


def predict_round(ledger: dict, topology: Topology | str, *,
                  trace_rounds: int = 1, efficiency: dict | None = None,
                  param_bytes: int | None = None) -> dict:
    """Roofline-predicted per-round device time of ``ledger`` on
    ``topology``.

    ``ledger`` maps category -> ``{"bytes_gb", "flops_g", ...}`` as
    built by utils/tracing.categorize_ops over a trace covering
    ``trace_rounds`` rounds (totals are divided down to one round).
    Returns ``{"predicted_ms", "bottleneck", "categories"}`` where each
    category carries its own ``predicted_ms`` + ``bottleneck`` and the
    top-level bottleneck is the largest summed term
    (compute/memory/collective).
    """
    if isinstance(topology, str):
        topology = get_topology(topology)
    if trace_rounds < 1:
        raise ValueError(f"trace_rounds must be >= 1, got {trace_rounds}")
    eff = {**DEFAULT_EFFICIENCY, **(efficiency or {})}
    n = topology.chips
    flops_rate = n * topology.peak_tflops * 1e12 * eff["mxu"]
    hbm_rate = n * topology.hbm_gbps * 1e9 * eff["hbm"]
    ici_rate = topology.ici_gbps * 1e9 * eff["ici"]  # per chip

    categories: dict[str, dict] = {}
    terms = {"compute": 0.0, "memory": 0.0, "collective": 0.0}
    total_s = 0.0
    for cat in sorted(ledger):
        entry = ledger[cat]
        nbytes = entry.get("bytes_gb", 0.0) * GIB / trace_rounds
        flops = entry.get("flops_g", 0.0) * 1e9 / trace_rounds
        if cat == "collective" and n > 1 and ici_rate > 0:
            # Traced collective volume is per-program; each chip moves
            # its 1/n share over its own ICI links.
            t = nbytes / n / ici_rate
            bound = "collective"
        else:
            t_compute = flops / flops_rate if flops_rate > 0 else 0.0
            t_memory = nbytes / hbm_rate if hbm_rate > 0 else 0.0
            t = max(t_compute, t_memory)
            bound = "compute" if t_compute > t_memory else "memory"
        terms[bound] += t
        total_s += t
        categories[cat] = {
            "predicted_ms": t * 1e3,
            "bottleneck": bound,
        }
    if param_bytes and n > 1 and ici_rate > 0:
        # FedAvg global-model exchange per round, absent from single-chip
        # traces: ring all-reduce volume 2 * params * (n-1)/n per chip.
        t_allreduce = 2.0 * param_bytes * (n - 1) / n / ici_rate
        terms["collective"] += t_allreduce
        total_s += t_allreduce
    bottleneck = max(terms, key=lambda k: terms[k]) if any(
        terms.values()
    ) else "memory"
    return {
        "predicted_ms": total_s * 1e3,
        "bottleneck": bottleneck,
        "categories": categories,
    }


def costmodel_record(ledger: dict, *, trace_rounds: int = 1,
                     anchor: str = DEFAULT_ANCHOR,
                     measured_ms: float | None = None,
                     topologies: dict | None = None,
                     efficiency: dict | None = None,
                     param_bytes: int | None = None,
                     run_rounds: int | None = None) -> dict:
    """The schema-v6 ``costmodel`` sub-object (ONE shape shared by the
    bench ``costmodel`` leg, the simulator's last-round metrics record,
    and scripts/report_run.py's "cost at scale" section — pinned by
    tests/data/metrics_record.schema.json).

    ``anchor`` names the topology the run was MEASURED on;
    ``model_error_ratio`` = anchor-predicted / measured per-round ms —
    the number compare_bench.py's ``--model-drift-threshold`` judges as
    an absolute band around 1.0. ``run_rounds`` (converged-run horizon)
    adds ``usd_per_run`` per topology.
    """
    topos = topologies if topologies is not None else TOPOLOGIES
    anchor_topo = (
        topos[anchor] if anchor in topos else get_topology(anchor)
    )
    anchor_pred = predict_round(
        ledger, anchor_topo, trace_rounds=trace_rounds,
        efficiency=efficiency, param_bytes=param_bytes,
    )
    per_topology = {}
    for name in sorted(topos):
        topo = topos[name]
        pred = (
            anchor_pred if name == anchor else predict_round(
                ledger, topo, trace_rounds=trace_rounds,
                efficiency=efficiency, param_bytes=param_bytes,
            )
        )
        entry = {
            "chips": topo.chips,
            "predicted_ms": round(pred["predicted_ms"], 3),
            "bottleneck": pred["bottleneck"],
            "usd_per_round": round(
                pred["predicted_ms"] / 3.6e6
                * topo.chips * topo.usd_per_chip_hour, 6
            ),
        }
        if run_rounds:
            entry["usd_per_run"] = round(
                entry["usd_per_round"] * run_rounds, 4
            )
        per_topology[name] = entry
    record = {
        "anchor_topology": anchor_topo.name,
        "predicted_ms": round(anchor_pred["predicted_ms"], 3),
        "measured_ms": (
            round(measured_ms, 3) if measured_ms is not None else None
        ),
        "model_error_ratio": (
            round(anchor_pred["predicted_ms"] / measured_ms, 4)
            if measured_ms else None
        ),
        "bottleneck": anchor_pred["bottleneck"],
        "trace_rounds": trace_rounds,
        "categories": {
            cat: {
                "bytes_gb": round(
                    ledger[cat].get("bytes_gb", 0.0) / trace_rounds, 3
                ),
                "device_ms": round(
                    ledger[cat].get("device_ms", 0.0) / trace_rounds, 2
                ),
                "flops_g": round(
                    ledger[cat].get("flops_g", 0.0) / trace_rounds, 2
                ),
                "predicted_ms": round(pred_c["predicted_ms"], 3),
                "bottleneck": pred_c["bottleneck"],
            }
            for cat, pred_c in anchor_pred["categories"].items()
        },
        "per_topology": per_topology,
    }
    if run_rounds:
        record["run_rounds"] = run_rounds
    return record


def sweep_cost_record(ledger: dict, *, trace_rounds: int = 1,
                      points: int, rounds_total: int,
                      programs_compiled: int,
                      executed_points: int | None = None,
                      anchor: str = DEFAULT_ANCHOR,
                      topologies: dict | None = None,
                      efficiency: dict | None = None,
                      param_bytes: int | None = None) -> dict:
    """$/sweep: price the compiled program ONCE, multiply by the sweep's
    round occupancy per topology (sweep/engine.py; ROADMAP item 1's
    "$/sweep per topology").

    ``ledger`` describes the sweep's (shared) round program over
    ``trace_rounds`` traced rounds; ``rounds_total`` is the sweep's
    total round occupancy (sum of every point's horizon — a vmapped
    fleet of E experiments over R rounds occupies E*R experiment-rounds
    even though it dispatches R programs, because each dispatch does E
    experiments' device work); ``programs_compiled`` over
    ``executed_points`` (default ``points``; a partially-resumed sweep
    compiled programs only for the points it actually ran) gives the
    compile-amortization bookkeeping
    (``compile_reuse_fraction`` — every point past each group's first
    rides a warm program, the multiplier the sweep engine exists for:
    BENCH_r05 measured 9.5 s compile vs 5.7 s useful run on the
    headline). Device-work cost does NOT amortize — only the compile
    does — so ``usd_per_sweep`` scales with occupancy while the compile
    column scales with programs.
    """
    if points < 1:
        raise ValueError(f"points must be >= 1, got {points}")
    if rounds_total < 1:
        raise ValueError(f"rounds_total must be >= 1, got {rounds_total}")
    if executed_points is None:
        executed_points = points
    topos = topologies if topologies is not None else TOPOLOGIES
    per_topology = {}
    for name in sorted(topos):
        topo = topos[name]
        pred = predict_round(
            ledger, topo, trace_rounds=trace_rounds,
            efficiency=efficiency, param_bytes=param_bytes,
        )
        usd_per_round = (
            pred["predicted_ms"] / 3.6e6 * topo.chips
            * topo.usd_per_chip_hour
        )
        per_topology[name] = {
            "chips": topo.chips,
            "predicted_round_ms": round(pred["predicted_ms"], 3),
            "bottleneck": pred["bottleneck"],
            "usd_per_sweep": round(usd_per_round * rounds_total, 6),
            "usd_per_point": round(
                usd_per_round * rounds_total / points, 6
            ),
        }
    return {
        "anchor_topology": (
            topos[anchor].name if anchor in topos
            else get_topology(anchor).name
        ),
        "points": points,
        "rounds_total": rounds_total,
        "programs_compiled": programs_compiled,
        "compile_reuse_fraction": (
            round(max(0.0, 1.0 - programs_compiled / executed_points), 4)
            if executed_points else None
        ),
        "trace_rounds": trace_rounds,
        "per_topology": per_topology,
    }
