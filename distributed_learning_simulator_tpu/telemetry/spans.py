"""Cross-host distributed tracing: span journals + crash flight recorder.

PR 15 made the simulator genuinely distributed, but every telemetry
layer stayed single-process: no way to see which HOST stalled an
allgather, how skewed barrier arrivals are, or what a killed host was
doing when it died. This module is the per-host half of the fix:

* :class:`SpanRecorder` — a trace-time-cheap structured span recorder.
  ``begin``/``end`` stamp ``clock.monotonic()`` and append one dict to a
  bounded in-memory ring (``deque(maxlen=...)``; overflow counts into
  ``dropped``, never blocks the hot path). ``flush()`` drains completed
  spans to a per-host ``spans_<host_id>.jsonl`` journal once per round.
* Flight recorder — the same ring, read out under failure. Spans marked
  ``eager=True`` (the per-round envelope, DCN barrier waits, checkpoint
  barriers — anything that can deadlock or die mid-span) additionally
  write an ``open`` journal line at BEGIN, flushed to the OS before the
  span body runs: a SIGKILL'd process leaves its open-line on disk, so
  the postmortem names the span it died inside without any cleanup code
  running. ``flush_inflight(reason)`` is the soft-failure path (SIGTERM,
  fault-quorum rejection, unhandled crash): last-K completed spans +
  a ``flight`` marker + one ``inflight`` line per still-open span.
* :class:`SpanPhaseTimer` — a proxy wrapping the existing
  :class:`~..telemetry.phases.PhaseTimer` (or its Null twin) so every
  phase boundary emits begin/end spans at ANY ``telemetry_level``,
  without touching the phase-accounting contract.

Journal line taxonomy (all JSONL, one object per line):

``header``   host identity + clock anchors (``epoch_wall``/``epoch_mono``
             sampled back-to-back) + ``clock_offset_s`` /
             ``clock_uncertainty_s`` vs host 0 — everything
             ``scripts/trace_timeline.py`` needs to stitch journals.
``open``     eager begin marker (flight recorder); matched by a later
             ``span`` line with the same ``id`` unless the host died.
``span``     completed span: ``t0`` (monotonic), ``dur`` seconds.
``event``    instant event (recompiles, dispatch marks).
``flight``   force-flush marker with the triggering ``reason`` and, when
             an exception unwound through a span first, the ``in_span``
             it escaped from (name/cat/round + exception type).
``inflight`` a span still open at force-flush time.

Span categories (``cat``): ``round`` (per-round envelope), ``phase``
(PhaseTimer phases), ``dcn_wait`` (barrier arrival waits — the skew
signal), ``dcn`` (payload collectives), ``io`` (checkpoint shard
writes), ``stream`` (prefetch worker occupancy), ``compile`` (recompile
events), ``dispatch``. ``round_summary()`` folds a round's spans into
the schema-v12 ``spans`` record sub-object (``utils/reporting.py``).

Everything here is jax-free and thread-safe (the streaming prefetch
worker emits occupancy spans from its own thread).

``span_trace='off'`` (default) constructs none of this — the simulator
keeps the exact pre-feature program (off-gate contract).
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading

from distributed_learning_simulator_tpu.telemetry import clock

JOURNAL_VERSION = 1

#: Journal filename for a host, next to metrics.jsonl in the artifacts
#: (or ``span_dir``) directory. The stitcher globs this pattern.
JOURNAL_PATTERN = "spans_{host_id}.jsonl"


def journal_filename(host_id: int) -> str:
    return JOURNAL_PATTERN.format(host_id=int(host_id))


class SpanRecorder:
    """Bounded in-memory span ring + per-host JSONL journal.

    Hot-path cost is one dict build and a deque append under a lock;
    journal I/O happens only in ``flush()`` (once per round), at eager
    begins (a handful per round), and in the failure paths.
    """

    def __init__(self, host_id: int = 0, n_hosts: int = 1,
                 capacity: int = 4096, flush_last_k: int = 64):
        if capacity < 1:
            raise ValueError(f"span buffer capacity must be >= 1: {capacity}")
        if flush_last_k < 1:
            raise ValueError(f"flush_last_k must be >= 1: {flush_last_k}")
        self.host_id = int(host_id)
        self.n_hosts = int(n_hosts)
        self.capacity = int(capacity)
        self.flush_last_k = int(flush_last_k)
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._open: dict[int, dict] = {}
        self._next_id = 0
        self._dropped = 0
        self._round_agg: dict[int, dict] = {}
        # Skews measured after a round's record already shipped (the
        # checkpoint barrier runs post-emit): parked here and merged
        # into the NEXT round_summary — "the most recent checkpoint
        # barrier's skew", never silently dropped.
        self._pending_skews: dict[str, float] = {}
        # Run-level aggregate for the result dict's span_summary.
        self._run = {"count": 0, "by_cat": {}, "skews": {}}
        # The innermost span an exception unwound through: by the time
        # the crash handler calls flush_inflight, every context-managed
        # span has already closed on the unwind, so this is the only
        # record of WHERE the failure struck — stamped onto the flight
        # marker as ``in_span``.
        self._last_error: dict | None = None
        self._file = None
        self.journal_path: str | None = None
        self._closed = False

    # ------------------------------------------------------------------
    # journal attachment

    def attach(self, directory: str, clock_offset_s: float = 0.0,
               clock_uncertainty_s: float = 0.0) -> str:
        """Open ``spans_<host_id>.jsonl`` under ``directory`` and write
        the header line (clock anchors + alignment). Returns the path."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, journal_filename(self.host_id))
        # Anchor the monotonic epoch: sample wall and monotonic
        # back-to-back so (epoch_wall, epoch_mono) name the same instant
        # up to a few microseconds.
        epoch_wall = clock.wall()
        epoch_mono = clock.monotonic()
        header = {
            "kind": "header",
            "journal_version": JOURNAL_VERSION,
            "host_id": self.host_id,
            "n_hosts": self.n_hosts,
            "pid": os.getpid(),
            "epoch_wall": epoch_wall,
            "epoch_mono": epoch_mono,
            "clock_offset_s": float(clock_offset_s),
            "clock_uncertainty_s": float(clock_uncertainty_s),
            "span_trace": "on",
        }
        with self._lock:
            self._file = open(path, "w", encoding="utf-8")
            self.journal_path = path
            self._file.write(json.dumps(header) + "\n")
            self._file.flush()
        return path

    # ------------------------------------------------------------------
    # span emission

    def begin(self, name: str, cat: str, round_idx: int | None = None,
              eager: bool = False, **attrs) -> int:
        """Open a span; returns its id for :meth:`end`.

        ``eager=True`` writes an ``open`` journal line immediately and
        flushes it to the OS — the flight-recorder guarantee that a
        SIGKILL mid-span still leaves the span's identity on disk.
        """
        t0 = clock.monotonic()
        span = {"id": -1, "name": name, "cat": cat, "t0": t0}
        if round_idx is not None:
            span["round"] = int(round_idx)
        if attrs:
            span["attrs"] = attrs
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            span["id"] = sid
            self._open[sid] = span
            if eager and self._file is not None:
                line = {"kind": "open", **{k: span[k] for k in span
                                           if k != "attrs"}}
                if attrs:
                    line["attrs"] = attrs
                self._file.write(json.dumps(line) + "\n")
                self._file.flush()
        return sid

    def end(self, span_id: int, **attrs) -> float:
        """Close a span; returns its duration in seconds. Extra attrs
        merge into the span record (e.g. measured skew on a wait)."""
        t1 = clock.monotonic()
        with self._lock:
            span = self._open.pop(span_id, None)
            if span is None:
                return 0.0
            dur = t1 - span["t0"]
            span["dur"] = dur
            if attrs:
                span.setdefault("attrs", {}).update(attrs)
            self._append_locked(span)
            self._aggregate_locked(span)
        return dur

    @contextlib.contextmanager
    def span(self, name: str, cat: str, round_idx: int | None = None,
             eager: bool = False, **attrs):
        """Context-manager form of begin/end. Yields a dict the body may
        mutate to attach result attrs (e.g. byte counts)."""
        extra: dict = {}
        sid = self.begin(name, cat, round_idx=round_idx, eager=eager,
                         **attrs)
        try:
            yield extra
        except BaseException as e:
            # Remember the innermost span this exception escaped from —
            # the span itself closes below (clean journals), but the
            # flight marker needs to name where the failure struck.
            err = {"name": name, "cat": cat, "error": type(e).__name__}
            if round_idx is not None:
                err["round"] = int(round_idx)
            with self._lock:
                if self._last_error is None:
                    self._last_error = err
            raise
        finally:
            self.end(sid, **extra)

    def event(self, name: str, cat: str, round_idx: int | None = None,
              **attrs) -> None:
        """Instant event (zero-duration mark: recompile, dispatch)."""
        ev = {"kind": "event", "name": name, "cat": cat,
              "t": clock.monotonic()}
        if round_idx is not None:
            ev["round"] = int(round_idx)
        if attrs:
            ev["attrs"] = attrs
        with self._lock:
            self._append_locked(ev)
            self._run["count"] += 1
            if round_idx is not None:
                agg = self._agg_for_locked(round_idx)
                agg["count"] += 1

    def note_skew(self, round_idx: int, key: str, skew_ms: float) -> None:
        """Record a measured barrier skew (``spill_skew_ms`` /
        ``ckpt_skew_ms``) into the round's summary. Max-aggregated: the
        worst skew a round saw is the one that bounds its critical path."""
        with self._lock:
            agg = self._agg_for_locked(round_idx)
            prev = agg["skews"].get(key)
            if prev is None or skew_ms > prev:
                agg["skews"][key] = float(skew_ms)
            self._note_run_skew_locked(key, skew_ms)

    def note_pending_skew(self, key: str, skew_ms: float) -> None:
        """Like :meth:`note_skew` for a barrier that ran AFTER its
        round's record shipped (the checkpoint barrier): merged into the
        next :meth:`round_summary` instead of a specific round's."""
        with self._lock:
            prev = self._pending_skews.get(key)
            if prev is None or skew_ms > prev:
                self._pending_skews[key] = float(skew_ms)
            self._note_run_skew_locked(key, skew_ms)

    # ------------------------------------------------------------------
    # draining

    def flush(self) -> int:
        """Drain completed spans/events to the journal. Returns the
        number of lines written (0 when unattached — the ring then just
        keeps the last ``capacity`` entries as a pure flight recorder)."""
        with self._lock:
            if self._file is None:
                return 0
            n = 0
            while self._ring:
                rec = self._ring.popleft()
                self._file.write(json.dumps(self._line_locked(rec)) + "\n")
                n += 1
            if n:
                self._file.flush()
            return n

    def flush_inflight(self, reason: str) -> int:
        """Force-flush for the failure paths (SIGTERM, quorum rejection,
        unhandled crash): last-K completed spans, a ``flight`` marker
        carrying ``reason``, then one ``inflight`` line per open span.
        Safe to call multiple times and with no journal attached."""
        with self._lock:
            if self._file is None or self._closed:
                return 0
            n = 0
            tail = list(self._ring)[-self.flush_last_k:]
            self._ring.clear()
            for rec in tail:
                self._file.write(json.dumps(self._line_locked(rec)) + "\n")
                n += 1
            flight = {
                "kind": "flight", "reason": str(reason),
                "t": clock.monotonic(), "wall": clock.wall(),
            }
            if self._last_error is not None:
                flight["in_span"] = self._last_error
            self._file.write(json.dumps(flight) + "\n")
            n += 1
            for span in self._open.values():
                line = {"kind": "inflight", "inflight": True,
                        **{k: span[k] for k in span}}
                self._file.write(json.dumps(line) + "\n")
                n += 1
            self._file.flush()
            try:
                os.fsync(self._file.fileno())
            except OSError:
                pass
            return n

    def close(self) -> None:
        """Final drain + close the journal (idempotent)."""
        self.flush()
        with self._lock:
            self._closed = True
            if self._file is not None:
                try:
                    self._file.flush()
                    self._file.close()
                finally:
                    self._file = None

    # ------------------------------------------------------------------
    # per-round summary (schema-v12 `spans` sub-object)

    def round_summary(self, round_idx: int) -> dict:
        """Pop the round's aggregate as the metrics-record sub-object.
        Pending post-emit skews (checkpoint barrier) merge in here."""
        with self._lock:
            agg = self._round_agg.pop(int(round_idx), None)
            dropped = self._dropped
            pending = self._pending_skews
            self._pending_skews = {}
        rec = {
            "host_id": self.host_id,
            "hosts": self.n_hosts,
            "count": 0 if agg is None else int(agg["count"]),
        }
        if dropped:
            rec["dropped"] = int(dropped)
        if agg is not None:
            if agg["by_cat"]:
                rec["seconds_by_cat"] = {
                    k: round(v, 6) for k, v in sorted(agg["by_cat"].items())
                }
            rec["dcn_wait_s"] = round(agg["by_cat"].get("dcn_wait", 0.0), 6)
            rec["dcn_transfer_s"] = round(agg["by_cat"].get("dcn", 0.0), 6)
            skews = dict(agg["skews"])
        else:
            skews = {}
        for k, v in pending.items():
            if skews.get(k) is None or v > skews[k]:
                skews[k] = v
        if agg is not None or skews:
            rec["spill_skew_ms"] = skews.get("spill_skew_ms")
            rec["ckpt_skew_ms"] = skews.get("ckpt_skew_ms")
        return rec

    def run_summary(self) -> dict:
        """Whole-run aggregate for the result dict's ``span_summary``
        (bench.py's mhost leg and the 2-process tests read it)."""
        with self._lock:
            run = {
                "count": int(self._run["count"]),
                "dropped": int(self._dropped),
                "by_cat": dict(self._run["by_cat"]),
                "skews": dict(self._run["skews"]),
            }
        return {
            "host_id": self.host_id,
            "hosts": self.n_hosts,
            "journal_path": self.journal_path,
            "count": run["count"],
            "dropped": run["dropped"],
            "seconds_by_cat": {
                k: round(v, 6) for k, v in sorted(run["by_cat"].items())
            },
            "dcn_wait_s": round(run["by_cat"].get("dcn_wait", 0.0), 6),
            "dcn_transfer_s": round(run["by_cat"].get("dcn", 0.0), 6),
            "spill_skew_ms_max": run["skews"].get("spill_skew_ms"),
            "ckpt_skew_ms_max": run["skews"].get("ckpt_skew_ms"),
        }

    # ------------------------------------------------------------------
    # internals (call with self._lock held)

    def _append_locked(self, rec: dict) -> None:
        if len(self._ring) == self._ring.maxlen:
            self._dropped += 1
        self._ring.append(rec)

    def _agg_for_locked(self, round_idx: int) -> dict:
        return self._round_agg.setdefault(int(round_idx), {
            "count": 0, "by_cat": {}, "skews": {},
        })

    def _aggregate_locked(self, span: dict) -> None:
        cat = span.get("cat", "")
        dur = span.get("dur", 0.0)
        self._run["count"] += 1
        self._run["by_cat"][cat] = self._run["by_cat"].get(cat, 0.0) + dur
        rnd = span.get("round")
        if rnd is None:
            return
        agg = self._agg_for_locked(rnd)
        agg["count"] += 1
        agg["by_cat"][cat] = agg["by_cat"].get(cat, 0.0) + dur

    def _note_run_skew_locked(self, key: str, skew_ms: float) -> None:
        prev = self._run["skews"].get(key)
        if prev is None or skew_ms > prev:
            self._run["skews"][key] = float(skew_ms)

    @staticmethod
    def _line_locked(rec: dict) -> dict:
        if rec.get("kind") == "event":
            return rec
        return {"kind": "span", **rec}


class SpanPhaseTimer:
    """PhaseTimer proxy: same phase-accounting contract, plus a span per
    phase. Wraps either timer class — spans work at any
    ``telemetry_level``, including 'off' (the Null inner still yields
    its inert fence box; only the span clocks run)."""

    def __init__(self, inner, recorder: SpanRecorder):
        self._inner = inner
        self._rec = recorder

    @property
    def enabled(self) -> bool:
        return self._inner.enabled

    @contextlib.contextmanager
    def phase(self, round_idx: int, name: str):
        # Dispatch boundary: the client_step phase entry IS where the
        # round program is handed to the runtime — an instant event so
        # the timeline marks it even under async dispatch (where the
        # phase's duration is trace+dispatch cost, not device time).
        if name == "client_step":
            self._rec.event("dispatch", "dispatch", round_idx=round_idx)
        # Span outside the inner phase: a fencing timer's
        # block_until_ready runs before the span closes, so 'detailed'
        # mode spans measure true device time like the phase table does.
        with self._rec.span(name, "phase", round_idx=round_idx):
            with self._inner.phase(round_idx, name) as box:
                yield box

    def take(self, round_idx: int):
        return self._inner.take(round_idx)

    def carve(self, round_idx: int, name: str, seconds: float,
              source: str) -> None:
        self._inner.carve(round_idx, name, seconds, source)
