"""XLA recompilation counter.

A shape-stable simulation compiles each of its programs exactly once, in
the first executed round (warmup: the round program, the eval program,
and assorted small host jits). Any backend compile AFTER warmup means an
operand's shape/dtype/static-arg changed across rounds — a
shape-instability bug that silently multiplies round cost (the round
program's compile is tens of seconds at flagship scale) — so the round
loop logs it as a WARNING with the offending function name.

Two hooks, combined:

* **Count** — a ``jax.monitoring`` duration listener on the
  ``/jax/core/compile/backend_compile_duration`` event: fires once per
  program LOWERED to the backend, including persistent-cache hits
  (verified on the pinned jax: the event wraps compile_or_get_cached
  unconditionally). That is the right instability signal — a cache hit
  still means a NEW program shape was traced this round — but it means
  the per-event duration, not the count alone, says whether the full
  compile cost was paid.
* **Names** — the monitoring event carries no function name in this JAX
  version, so the monitor additionally flips ``jax_log_compiles`` on and
  captures the ``"Finished XLA compilation of jit(<name>) …"`` lines
  from the ``jax._src.dispatch`` logger. While the monitor is active,
  propagation on the two chatty compile loggers is suspended so the
  capture doesn't spam stderr; both the flag and propagation are
  restored on ``stop()``.

One monitor active per process at a time (it owns process-global logging
state); the simulator scopes it to the round loop.
"""

from __future__ import annotations

import logging
import re
import threading

import jax

try:  # the unregister helpers are private; degrade to a dead-listener guard
    from jax._src import monitoring as _monitoring_src
except Exception:  # pragma: no cover - import layout change
    _monitoring_src = None

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_COMPILE_LOGGER = "jax._src.dispatch"
# "Compiling <fn> with global shapes…" (pxla) and "Persistent compilation
# cache hit…" (compiler) log at the same forced-WARNING level; suspend
# their propagation too while jax_log_compiles is on.
_CHATTY_LOGGERS = (
    _COMPILE_LOGGER,
    "jax._src.interpreters.pxla",
    "jax._src.compiler",
)
_FINISHED_RE = re.compile(
    r"Finished XLA compilation of (?:jit\()?([^)\s]+)\)? in ([0-9.eE+-]+) sec"
)


class _CaptureHandler(logging.Handler):
    def __init__(self, monitor: "RecompileMonitor"):
        super().__init__(level=logging.DEBUG)
        self._monitor = monitor

    def emit(self, record: logging.LogRecord) -> None:
        try:
            m = _FINISHED_RE.search(record.getMessage())
        except Exception:  # pragma: no cover - malformed record
            return
        if m:
            self._monitor._record_name(m.group(1), float(m.group(2)))


class RecompileMonitor:
    """Counts XLA backend compiles and attributes them to rounds.

    Usage (the simulator's round loop)::

        with RecompileMonitor() as mon:
            for round_idx in ...:
                dispatch(...)
                mon.attribute(round_idx)   # drain events -> this round
            ...
            events = mon.take(round_idx)   # [(fn_name, seconds), ...]
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._count = 0          # monitoring-event ground truth
        self._named: list[tuple[str, float]] = []
        self._per_round: dict[int, list[tuple[str, float]]] = {}
        self._active = False
        self._handler: _CaptureHandler | None = None
        self._saved_log_compiles = False
        self._saved_propagate: dict[str, bool] = {}
        self._null_handlers: dict[str, logging.Handler] = {}

    # -- listener callbacks ---------------------------------------------------
    def _on_duration(self, event: str, duration: float, **kwargs) -> None:
        if not self._active or event != _COMPILE_EVENT:
            return
        with self._lock:
            self._count += 1

    def _record_name(self, name: str, seconds: float) -> None:
        if not self._active:
            return
        with self._lock:
            self._named.append((name, seconds))

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "RecompileMonitor":
        if self._active:
            return self
        self._active = True
        jax.monitoring.register_event_duration_secs_listener(self._on_duration)
        self._handler = _CaptureHandler(self)
        logging.getLogger(_COMPILE_LOGGER).addHandler(self._handler)
        self._null_handlers = {}
        for name in _CHATTY_LOGGERS:
            lg = logging.getLogger(name)
            self._saved_propagate[name] = lg.propagate
            lg.propagate = False
            # propagate=False alone is not silence: a record that finds NO
            # handler anywhere falls through to logging.lastResort (which
            # prints WARNINGs to stderr) — park a NullHandler so the
            # forced-on compile chatter has a sink.
            nh = logging.NullHandler()
            self._null_handlers[name] = nh
            lg.addHandler(nh)
        self._saved_log_compiles = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        return self

    def stop(self) -> None:
        if not self._active:
            return
        self._active = False
        jax.config.update("jax_log_compiles", self._saved_log_compiles)
        for name, prop in self._saved_propagate.items():
            logging.getLogger(name).propagate = prop
        self._saved_propagate.clear()
        for name, nh in getattr(self, "_null_handlers", {}).items():
            logging.getLogger(name).removeHandler(nh)
        self._null_handlers = {}
        if self._handler is not None:
            logging.getLogger(_COMPILE_LOGGER).removeHandler(self._handler)
            self._handler = None
        if _monitoring_src is not None:
            try:
                _monitoring_src._unregister_event_duration_listener_by_callback(
                    self._on_duration
                )
            except Exception:
                # Listener stays registered but self._active gates it to a
                # no-op; harmless beyond a dict entry.
                pass

    def __enter__(self) -> "RecompileMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- draining -------------------------------------------------------------
    def drain(self) -> list[tuple[str, float]]:
        """Pop the events recorded since the last drain as
        ``[(fn_name, compile_seconds), ...]``. The monitoring count is the
        ground truth; if a JAX upgrade changes the log format and names go
        missing, the shortfall is padded with ``"<unknown>"`` entries so
        the COUNT is never under-reported."""
        with self._lock:
            named, self._named = self._named, []
            count, self._count = self._count, 0
        while len(named) < count:
            named.append(("<unknown>", 0.0))
        return named

    def attribute(self, round_idx: int) -> None:
        """Drain pending events into ``round_idx``'s bucket. Called right
        after each dispatch site (compiles are synchronous with trace/
        lower, so events pending here belong to the calls just made)."""
        events = self.drain()
        if events:
            self._per_round.setdefault(round_idx, []).extend(events)

    def take(self, round_idx: int) -> list[tuple[str, float]]:
        """Pop the events attributed to ``round_idx``."""
        return self._per_round.pop(round_idx, [])


def log_round_compiles(
    logger: logging.Logger,
    round_idx: int,
    events: list[tuple[str, float]],
    warmup: bool,
) -> int:
    """Log a round's compile events; returns the count.

    Warmup compiles (the first executed round) are expected and logged at
    INFO. Post-warmup compiles are the shape-instability signal — logged
    as a WARNING naming the offending function(s) so the bug is
    attributable without a profiler. (The memoized Shapley subset
    evaluator legitimately compiles new wave shapes in later rounds —
    docs/OBSERVABILITY.md covers reading the names.)
    """
    if not events:
        return 0
    names = ", ".join(f"{name} ({secs:.1f}s)" for name, secs in events)
    if warmup:
        logger.info(
            "round %d: %d XLA compile(s) during warmup: %s",
            round_idx, len(events), names,
        )
    else:
        logger.warning(
            "round %d: %d XLA recompile(s) AFTER warmup — shape-unstable "
            "round program? offending: %s",
            round_idx, len(events), names,
        )
    return len(events)
