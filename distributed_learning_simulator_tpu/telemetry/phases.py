"""Per-round phase timing around the simulator's ``annotate()`` regions.

A round's wall-clock splits into: ``client_step`` (the fused round
program's dispatch — local training AND the in-program aggregation; XLA
fuses them, so they are one phase by construction), ``aggregate`` (the
server-optimizer post-step, when configured), ``eval``, ``host_sync``
(the deferred device->host metric fetch), ``post_round`` (host-side
algorithm work, e.g. Shapley scoring), and — under streamed residency
with a sampled cohort — ``sample`` (the host-side cohort-draw replay,
``parallel/streaming.CohortStreamer.cohort_for``; carved out of the
``client_step`` window it overlaps via :meth:`PhaseTimer.carve`).

Two fidelity modes, selected by ``config.telemetry_level``:

* ``basic`` — monotonic clocks only. JAX dispatch is asynchronous, so a
  dispatch phase measures trace+dispatch cost while the device time it
  launched pools into whichever later phase first blocks (usually
  ``host_sync``). Zero perturbation of the measured program.
* ``detailed`` — each phase fences on its output
  (``jax.block_until_ready``) before the clock stops, so the split is
  true per-phase device time. Fencing serializes dispatch with
  execution, which defeats round pipelining's transfer/compute overlap —
  a measurement mode, not a production mode.

``telemetry_level='off'`` gets the :class:`NullPhaseTimer`, whose phase
contexts are no-ops — the default program is untouched.
"""

from __future__ import annotations

import contextlib

import jax

from distributed_learning_simulator_tpu.telemetry import clock


class _FenceBox:
    """Mutable slot a phase body parks its output in; a fencing timer
    blocks on it before stopping the clock (``fence`` is a no-op record
    under ``basic`` — the value is simply not waited on)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def fence(self, value) -> None:
        self.value = value


class PhaseTimer:
    """Accumulates per-(round, phase) wall-clock seconds.

    Keyed by round index because round pipelining interleaves rounds:
    round r's ``host_sync``/``post_round`` run after round r+1's
    ``client_step`` has been dispatched. ``take(round_idx)`` pops the
    finished round's dict for its metrics record.
    """

    enabled = True

    def __init__(self, fence: bool = False):
        self._fence = fence
        self._acc: dict[int, dict[str, float]] = {}

    @contextlib.contextmanager
    def phase(self, round_idx: int, name: str):
        box = _FenceBox()
        t0 = clock.monotonic()
        try:
            yield box
        finally:
            if self._fence and box.value is not None:
                jax.block_until_ready(box.value)
            dt = clock.monotonic() - t0
            acc = self._acc.setdefault(round_idx, {})
            acc[name] = acc.get(name, 0.0) + dt

    def take(self, round_idx: int) -> dict[str, float]:
        """Pop the round's accumulated phase seconds (empty dict if the
        round recorded nothing)."""
        return self._acc.pop(round_idx, {})

    def carve(self, round_idx: int, name: str, seconds: float,
              source: str) -> None:
        """Re-attribute ``seconds`` of host work from the OPEN ``source``
        phase window to its own named phase.

        Used for the streamed cohort-draw replay (``sample``): the draw
        for the next dispatch deliberately runs after the current
        dispatch launches — inside the ``client_step`` region, so it
        overlaps device compute — but its host cost (the ~1 s exact
        replay at N=1e6) must be visible in the phase table, not hidden
        in ``client_step``. The negative accumulation nets out when the
        enclosing context exits and adds its full wall; phases stay
        disjoint.
        """
        acc = self._acc.setdefault(round_idx, {})
        acc[name] = acc.get(name, 0.0) + seconds
        acc[source] = acc.get(source, 0.0) - seconds


class NullPhaseTimer:
    """``telemetry_level='off'``: same API, no clocks, no records."""

    enabled = False

    @contextlib.contextmanager
    def phase(self, round_idx: int, name: str):
        yield _FenceBox()

    def take(self, round_idx: int) -> None:
        return None

    def carve(self, round_idx: int, name: str, seconds: float,
              source: str) -> None:
        return None


def make_phase_timer(level: str) -> PhaseTimer | NullPhaseTimer:
    """Level -> timer: 'off' is inert, 'basic' clocks without fencing,
    'detailed' fences each phase on its output."""
    level = level.lower()
    if level == "off":
        return NullPhaseTimer()
    return PhaseTimer(fence=(level == "detailed"))
