"""Tracing/profiling: jax.profiler integration.

The reference has NO profiling instrumentation (SURVEY §5: the only
performance-adjacent output is compression-ratio logging). This module
exceeds parity: named trace annotations around the round / eval / post-round
phases (visible in TensorBoard/Perfetto), plus an opt-in programmatic
profiler session writing an XPlane trace directory.

Usage: set ``config.profile_dir`` — the simulator wraps the run in
``start_trace``/``stop_trace`` and annotates each phase.
"""

from __future__ import annotations

import contextlib

import jax


def annotate(name: str):
    """Named region visible in TPU traces (wraps jax.profiler annotations)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def profile_session(profile_dir: str | None):
    """Profile the enclosed block into ``profile_dir`` (no-op if None)."""
    if not profile_dir:
        yield
        return
    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
