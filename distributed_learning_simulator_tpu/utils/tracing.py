"""Tracing/profiling: jax.profiler integration.

The reference has NO profiling instrumentation (SURVEY §5: the only
performance-adjacent output is compression-ratio logging). This module
exceeds parity: named trace annotations around the round / eval / post-round
phases (visible in TensorBoard/Perfetto), plus an opt-in programmatic
profiler session writing an XPlane trace directory.

Usage: set ``config.profile_dir`` — the simulator wraps the run in
``start_trace``/``stop_trace`` and annotates each phase.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re

import jax


def iter_device_ops(trace_dir: str):
    """Yield device-lane op events from a jax.profiler trace directory.

    The ONE copy of the event-selection rule (shared by
    :func:`parse_device_trace` and the profiling scripts): complete ('X')
    events carrying XLA op annotations (``long_name`` or
    ``raw_bytes_accessed``), with parent ``while``/``jit(...)`` frames
    excluded — those wrap their children's time and would double count.
    Missing/empty trace dirs yield nothing rather than raising.

    Two assumptions callers must hold (ADVICE r4):

    * ``trace_dir`` must hold exactly ONE profiling session. Every
      ``*.trace.json.gz`` under the directory is summed, so a reused
      directory accumulates stale sessions into the totals. bench.py's
      proxy uses a fresh ``TemporaryDirectory`` per run; the profiling
      scripts ``rm -rf`` their target first.
    * Parent-frame exclusion is by the ``while``/``jit(`` name prefixes —
      the two wrapper frames XLA emits for these programs (whole-program
      jit frame, round/epoch/step ``while`` loops). A program whose
      byte-carrying ops sit under differently-named wrapper frames that
      also carry ``raw_bytes_accessed`` would double count; if a new
      wrapper family appears, extend the prefix list and re-baseline the
      proxy totals.
    """
    paths = glob.glob(
        os.path.join(trace_dir, "plugins", "profile", "*",
                     "*.trace.json.gz")
    )
    for path in sorted(paths, key=os.path.getmtime):
        with gzip.open(path, "rt") as f:
            data = json.load(f)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            args = ev.get("args") or {}
            if "long_name" not in args and "raw_bytes_accessed" not in args:
                continue
            name = ev.get("name", "")
            if name.startswith("while") or name.startswith("jit("):
                continue
            yield ev


def parse_device_trace(trace_dir: str) -> dict:
    """Aggregate device-op statistics from a jax.profiler trace directory.

    Returns ``{"device_ms", "bytes_gb", "op_count"}`` summed over
    :func:`iter_device_ops`. ``bytes_gb`` sums XLA's ``raw_bytes_accessed``
    — a DETERMINISTIC function of the compiled program (identical across
    runs of the same program on the same shapes), which makes it the
    environment-robust regression proxy bench.py emits: host contention
    moves wall-clock but cannot move the bytes the program accesses.
    CPU traces without byte annotations report zero bytes.
    """
    device_us = 0.0
    bytes_total = 0.0
    op_count = 0
    for ev in iter_device_ops(trace_dir):
        args = ev.get("args") or {}
        device_us += float(ev.get("dur", 0.0))
        bytes_total += float(args.get("raw_bytes_accessed", 0) or 0)
        op_count += 1
    return {
        "device_ms": device_us / 1e3,
        "bytes_gb": bytes_total / 2**30,
        "op_count": op_count,
    }


# Stage-attribution rules for the flagship ResNet-18 chunk-40 program
# (promoted from scripts/trace_categories.py, which is now a thin CLI
# wrapper): shape signatures in ``long_name`` -> pipeline stage. Ordered;
# first match wins. These are program-specific by design — the generic
# op-CLASS classification the cost model uses is :func:`classify_op`.
STAGE_RULES = [
    ("s4_wgrad", r"3,3,512,512.*fusion\(|fusion.*= f32\[3,3,512,512\]"),
    ("s3_wgrad", r"= f32\[3,3,256,256\]"),
    ("s2_wgrad", r"= f32\[3,3,128,128\]"),
    ("s1_wgrad", r"= f32\[3,3,128,40,128\]|= f32\[3,4,3,40,128\]|= f32\[3,2,128,40,"),
    ("stage4", r"4,4,512|2,2,512"),
    ("stage3", r"8,8,256"),
    ("stage2", r"16,16,128"),
    # stage-1 folded activations: NHWC [.., 32, 16, 128] (rounds 3-4) or
    # HWNC [32, 16, .., 128] (round 5); packed kernels/grads either way.
    ("stage1f", r"32,16,128|32,16,40,25,128|32,16,1000,128"
                r"|3,3,128,40,128|3,4,3,40,128"),
    ("dense/head", r"512,10|,10\]"),
    ("decode", r"u8\[|s32\["),
]

# Generic HLO op classes for the roofline cost model
# (telemetry/costmodel.py): every traced device op lands in exactly one.
OP_CLASSES = (
    "matmul_conv",   # MXU work: dots, convolutions, their fusions
    "elementwise",   # VPU work: loop/input fusions, reduces, converts
    "copy_layout",   # pure data movement: copies, transposes, bitcasts
    "collective",    # cross-chip: all-reduce/-gather/-to-all, permutes
    "decode",        # uint8 shard decode (compact_client_data path)
    "other",
)

_COLLECTIVE_MARKS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-broadcast",
)
_COPY_PREFIXES = ("copy", "transpose", "bitcast")
# "convolution", not "conv": XLA's elementwise converts
# ("convert_reduce_fusion") must not read as MXU work.
_MATMUL_MARKS = ("convolution", "dot", "einsum", "gemm", "matmul")


def classify_op(name: str, long_name: str = "") -> str:
    """Map one device op to its :data:`OP_CLASSES` bucket.

    Classification reads the op NAME first (XLA names fusions after their
    root/hero op: ``convolution_convert_fusion``, ``loop_reduce_fusion``,
    ``all-reduce.1``) and falls back to ``long_name`` markers. Order
    matters and is part of the contract (tests/test_tracing.py):
    collectives before matmul (an all-reduce OF conv grads is collective
    volume, not MXU work), decode before elementwise (the u8 shard
    decode is its own byte budget), copies only by name PREFIX (a
    ``fusion`` whose long_name merely mentions copy is not a copy).
    """
    lowered = name.lower()
    if any(m in lowered for m in _COLLECTIVE_MARKS):
        return "collective"
    if lowered.startswith(_COPY_PREFIXES):
        return "copy_layout"
    if "u8[" in long_name:
        # The compact_client_data shard decode specifically — s32 is NOT
        # a decode mark here: eval argmax outputs and cohort-index
        # streams carry s32 and must keep their own class (STAGE_RULES
        # keeps the wider u8|s32 rule for the flagship stage map).
        return "decode"
    if any(m in lowered for m in _MATMUL_MARKS) or (
        "dot_general" in long_name or "convolution" in long_name
    ):
        return "matmul_conv"
    if lowered.startswith(("fusion", "loop_", "input_", "reduce", "convert",
                           "broadcast", "select", "add", "multiply",
                           "subtract", "compare", "iota", "rng")):
        return "elementwise"
    return "other"


def categorize_long_name(long_name: str, rules=STAGE_RULES) -> str:
    """First-match rule category of one op's ``long_name`` (the stage
    attribution scripts/trace_categories.py prints); "other" when no
    rule matches."""
    for cat, pat in rules:
        if re.search(pat, long_name):
            return cat
    return "other"


def categorize_ops(trace_dir: str, rules=None) -> dict[str, dict]:
    """Categorized op LEDGER of a trace directory — the cost model's
    input (telemetry/costmodel.py) and the shared core of
    scripts/trace_categories.py.

    One pass over :func:`iter_device_ops` (the SAME selection rule as the
    bench proxy — wrapper ``while``/``jit(`` frames excluded, so ledger
    totals reconcile with :func:`parse_device_trace`), aggregating per
    category: ``{"device_ms", "bytes_gb", "flops_g", "op_count"}``.
    ``flops_g`` sums the per-op ``flops`` annotation where the trace
    carries one (TPU op profiles; absent on CPU traces and on most
    tunneled-chip traces, in which case the ledger is byte/time-only and
    the roofline model runs memory-side only — the measured programs ARE
    memory-bound, docs/PERFORMANCE.md).

    ``rules=None`` classifies into the generic :data:`OP_CLASSES` via
    :func:`classify_op`; passing an ordered ``[(category, regex), ...]``
    list (e.g. :data:`STAGE_RULES`) attributes by ``long_name`` instead.
    Missing/empty trace dirs return an empty ledger, never raise.
    """
    ledger: dict[str, dict] = {}
    for ev in iter_device_ops(trace_dir):
        args = ev.get("args") or {}
        long_name = args.get("long_name", "")
        if rules is not None:
            cat = categorize_long_name(long_name, rules)
        else:
            cat = classify_op(ev.get("name", ""), long_name)
        entry = ledger.setdefault(cat, {
            "device_ms": 0.0, "bytes_gb": 0.0, "flops_g": 0.0,
            "op_count": 0,
        })
        entry["device_ms"] += float(ev.get("dur", 0.0)) / 1e3
        entry["bytes_gb"] += float(
            args.get("raw_bytes_accessed", 0) or 0
        ) / 2**30
        entry["flops_g"] += float(args.get("flops", 0) or 0) / 1e9
        entry["op_count"] += 1
    return ledger


def top_device_ops(trace_dir: str, k: int = 10,
                   by: str = "bytes") -> list[dict]:
    """Top-``k`` device ops aggregated by op name over
    :func:`iter_device_ops`, ranked ``by`` "bytes" (time as tiebreaker —
    the default) or "time" (bytes as tiebreaker).

    The offline run reporter (scripts/report_run.py) renders both
    rankings — "where did the bytes go" and "where did the time go";
    same selection rule as the bench proxy, so an op that moves the
    proxy total is findable by name here. The bytes ranking is the
    deterministic one (bytes are a program property); the time ranking
    reflects the traced run's actual schedule, noise included.
    """
    return _rank_ops(_aggregate_device_ops(trace_dir), k, by)


def _aggregate_device_ops(trace_dir: str) -> dict[str, dict]:
    """Per-op-name byte/time/count aggregation over ONE pass of
    :func:`iter_device_ops` (the gzipped trace read is the expensive
    part — callers wanting several rankings aggregate once)."""
    agg: dict[str, dict] = {}
    for ev in iter_device_ops(trace_dir):
        args = ev.get("args") or {}
        name = ev.get("name", "<unnamed>")
        entry = agg.setdefault(
            name, {"name": name, "bytes_gb": 0.0, "device_ms": 0.0,
                   "count": 0}
        )
        entry["bytes_gb"] += float(args.get("raw_bytes_accessed", 0) or 0)
        entry["device_ms"] += float(ev.get("dur", 0.0)) / 1e3
        entry["count"] += 1
    for entry in agg.values():
        entry["bytes_gb"] = entry["bytes_gb"] / 2**30
    return agg


def _rank_ops(agg: dict[str, dict], k: int, by: str) -> list[dict]:
    if by not in ("bytes", "time"):
        raise ValueError(f"by must be 'bytes' or 'time', got {by!r}")
    ranked = sorted(
        agg.values(),
        key=(
            (lambda e: (e["bytes_gb"], e["device_ms"])) if by == "bytes"
            else (lambda e: (e["device_ms"], e["bytes_gb"]))
        ),
        reverse=True,
    )
    return ranked[:k]


def device_op_report(trace_dir: str, k: int = 10) -> dict:
    """Everything the offline reporter needs from a trace dir in ONE
    gzip pass: ``{"totals", "by_bytes", "by_time"}`` — the
    :func:`parse_device_trace` totals plus both top-op rankings."""
    agg = _aggregate_device_ops(trace_dir)
    return {
        "totals": {
            "device_ms": sum(e["device_ms"] for e in agg.values()),
            "bytes_gb": sum(e["bytes_gb"] for e in agg.values()),
            "op_count": sum(e["count"] for e in agg.values()),
        },
        "by_bytes": _rank_ops(agg, k, "bytes"),
        "by_time": _rank_ops(agg, k, "time"),
    }


def annotate(name: str):
    """Named region visible in TPU traces (wraps jax.profiler annotations)."""
    return jax.profiler.TraceAnnotation(name)


@contextlib.contextmanager
def profile_session(profile_dir: str | None):
    """Profile the enclosed block into ``profile_dir`` (no-op if None)."""
    if not profile_dir:
        yield
        return
    jax.profiler.start_trace(profile_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
