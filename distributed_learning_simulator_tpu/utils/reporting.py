"""Shared construction of tracked benchmark records.

One definition of each tracked metric's shape, so the driver-facing
emitters (bench.py sub-objects, scripts/* JSON lines) cannot drift into
reporting incomparable numbers for the same cost unit.
"""

from __future__ import annotations


def gtg_round_record(history, **extra):
    """The tracked converged-GTG round-cost record (``gtg_round_seconds``),
    shared by bench.py's ``gtg`` sub-object and
    scripts/measure_gtg_scale.py.

    Rounds whose walk never ran (round-truncated:
    ``gtg_permutations == 0``) are not comparable cost points, so the
    record reports the LAST full walk — a converged round is the honest
    cost unit; ``converged`` says whether this one was. Falls back to the
    final round when every round truncated (still inspectable), and
    returns None for an empty history. ``extra`` keys (knobs, peak HBM)
    are merged into the record.
    """
    if not history:
        return None
    walked = [h for h in history if h.get("gtg_permutations")]
    h = walked[-1] if walked else history[-1]
    record = {
        "metric": "gtg_round_seconds",
        "value": round(h["round_seconds"], 1),
        "round": h["round"],
        "converged": bool(h.get("gtg_converged")),
        "permutations": h.get("gtg_permutations"),
        "subset_evals": h.get("gtg_subset_evals"),
    }
    record.update(extra)
    return record
