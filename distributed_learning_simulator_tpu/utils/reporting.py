"""Shared construction of tracked benchmark + metrics records.

One definition of each tracked record's shape — the per-round
metrics.jsonl line (schema-versioned, telemetry-aware), the bench
provenance stamp, and the converged-GTG cost record — so the emitters
(simulator.py, execution/threaded.py, bench.py sub-objects, scripts/*
JSON lines) cannot drift into reporting incomparable numbers for the
same cost unit.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

# metrics.jsonl layout version. v1 (implicit — no version field) is the
# pre-telemetry record: round/test_accuracy/test_loss/… only. v2 adds
# ``schema_version`` and the ``telemetry`` sub-object (phase_seconds,
# compiles, peak_hbm_bytes; docs/OBSERVABILITY.md). v3 adds the
# ``client_stats`` sub-object (per-client quantile summaries, flagged
# ids + reasons; telemetry/client_stats.py). v4 adds the ``async``
# sub-object (deadline-round outcomes, staleness-buffer occupancy, the
# simulated clock; robustness/arrivals.py). v5 adds the ``stream``
# sub-object (per-dispatch host<->HBM transfer bytes/seconds and the
# prefetch overlap ratio; client_residency='streamed',
# parallel/streaming.py). v6 adds the ``costmodel`` sub-object (the
# roofline cost model's per-topology round-time/cost prediction with
# model-vs-measured error ratio; telemetry/costmodel.py — attached to
# the run's LAST record when config.cost_model_trace is set). v7 adds
# the ``valuation`` sub-object (the streaming per-client contribution
# vector's fold inputs and top/bottom tables, and — on audit rounds —
# the truncated-GTG cross-validation correlations;
# telemetry/valuation.py). v8 adds the ``sweep`` sub-object (which
# sweep point a record belongs to, the execution strategy, the point's
# config-hash group, and whether its program was reused warm;
# sweep/engine.py). v9 adds the ``population`` sub-object (the
# dynamic-population registration stream's per-round outcome: alive/
# registered counts, joins, departures — total and in-cohort — the
# planted drift cohort, and the rejected-by-churn flag;
# robustness/population.py). v10 adds the ``gtg`` sub-object (the
# mesh-sharded GTG walk's provenance: devices the subset-evaluation
# batch axis partitioned over, subset-eval throughput, the fused-call
# wave width, and the walk's wall seconds; algorithms/shapley.py —
# attached only on rounds whose walk actually sharded). v11 adds the
# ``multihost`` sub-object (the distributed shard store's per-host
# assembly provenance: host count, this host's id/owned-client
# count/shard bytes, the round's spill rows + bytes over DCN, and this
# host's h2d/overlap; parallel/streaming.DistributedCohortStreamer —
# attached only under client_residency='streamed' with >1 host
# process). v12 adds the ``spans`` sub-object (the distributed tracing
# layer's per-round per-host summary: span/drop counts, per-category
# seconds, DCN wait vs transfer, and the measured spill/checkpoint
# barrier skews; telemetry/spans.py — attached only under
# span_trace='on'). A record
# is stamped with the LOWEST version that describes it:
# telemetry_level='off' keeps emitting v1 byte-for-byte,
# client_stats='off' keeps telemetry-only records at v2 byte-for-byte,
# async_mode='off' keeps records at v3 or below, client_residency=
# 'resident' keeps records at v4 or below, cost_model_trace=None
# keeps records at v5 or below, client_valuation='off' keeps
# records at v6 or below, solo (non-sweep) runs keep records at v7
# or below, population='static' keeps records at v8 or below,
# serial (single-device) GTG walks keep records at v9 or below,
# single-process runs keep records at v10 or below, and
# span_trace='off' keeps records at v11 or below —
# longitudinal tooling never sees a
# layout change it didn't opt into.
METRICS_SCHEMA_VERSION = 12
_MULTIHOST_SCHEMA_VERSION = 11
_GTG_SCHEMA_VERSION = 10
_POPULATION_SCHEMA_VERSION = 9
_SWEEP_SCHEMA_VERSION = 8
_VALUATION_SCHEMA_VERSION = 7
_COSTMODEL_SCHEMA_VERSION = 6
_STREAM_SCHEMA_VERSION = 5
_ASYNC_SCHEMA_VERSION = 4
_CLIENT_STATS_SCHEMA_VERSION = 3
_TELEMETRY_ONLY_SCHEMA_VERSION = 2

# bench.py output version. v1 (implicit) had no provenance; v2 stamps
# ``schema_version`` + ``config_hash`` so scripts/compare_bench.py can
# refuse to diff incomparable runs.
BENCH_SCHEMA_VERSION = 2

# Config fields that do NOT define the measured program: two runs
# differing only in these are still comparable cost points. Everything
# else (model, population, chunking, dtypes, failure knobs, ...) lands in
# the hash. ``round`` is excluded because per-round medians are
# comparable across run lengths (bench records its rounds separately).
# ``telemetry_level`` is deliberately NOT excluded: 'detailed' fences
# every phase and defeats round pipelining, so its wall-clock is not a
# comparable cost point against an unfenced run.
_NON_PROGRAM_FIELDS = (
    "round",
    "log_root",
    "log_level",
    # Host-side detector sensitivity only (telemetry/client_stats.py):
    # never touches the compiled program or any measured cost, so tuning
    # it must not make bench runs incomparable. The other client-stats
    # knobs (on/off, cadence, probe size) DO change the program or its
    # transfer volume and stay in the hash.
    "client_stats_mad_threshold",
    "compilation_cache_dir",
    "profile_dir",
    "profile_from_round",
    # Cost-model knobs (telemetry/costmodel.py): pure host-side analysis
    # of an already-captured trace — never touches the compiled program
    # or any measured cost, so pricing a run must not make it
    # incomparable to an unpriced one.
    "cost_model_trace",
    "cost_model_trace_rounds",
    "cost_model_topology",
    "checkpoint_dir",
    "checkpoint_every",
    "checkpoint_keep_last",
    "resume",
    "data_dir",
    # Span-journal routing (telemetry/spans.py): where the per-host
    # jsonl lands — pure I/O, never the measured program. The other
    # span knobs off-gate out of the hash below instead (an ACTIVE
    # trace adds instrumentation overhead to the measured round).
    "span_dir",
    # Sweep persistence knobs (sweep/engine.py): where completed points
    # land and whether to resume — pure I/O, never the measured program.
    "sweep_dir",
    "sweep_resume",
)


def build_round_record(base: dict, telemetry: dict | None = None,
                       client_stats: dict | None = None,
                       async_federation: dict | None = None,
                       stream: dict | None = None,
                       costmodel: dict | None = None,
                       valuation: dict | None = None,
                       sweep: dict | None = None,
                       population: dict | None = None,
                       gtg: dict | None = None,
                       multihost: dict | None = None,
                       spans: dict | None = None) -> dict:
    """The ONE per-round metrics.jsonl record builder (vmap simulator and
    threaded oracle both write through this).

    All sub-objects ``None`` (``telemetry_level='off'``,
    ``client_stats='off'``, ``async_mode='off'``,
    ``client_residency='resident'``) returns ``base`` unchanged — the
    legacy v1 layout, byte-identical to pre-telemetry builds. A
    telemetry dict alone upgrades the record to v2 (``schema_version``
    + the ``telemetry`` sub-object — byte-identical to pre-client-stats
    v2 builds); a client_stats dict (telemetry/client_stats.py
    ``client_stats_record``) upgrades it to v3; an async dict (the
    simulator's per-round deadline/buffer outcome) upgrades it to v4
    under the ``"async"`` key; a stream dict (the streamer's
    per-dispatch transfer stats, parallel/streaming.py) upgrades it to
    v5 under the ``"stream"`` key; a costmodel dict
    (telemetry/costmodel.costmodel_record) upgrades it to v6 under the
    ``"costmodel"`` key; a valuation dict
    (telemetry/valuation.valuation_record) upgrades it to v7 under the
    ``"valuation"`` key; a sweep dict (sweep/engine.py per-point
    provenance) upgrades it to v8 under the ``"sweep"`` key; a
    population dict (robustness/population.PopulationModel.round_record)
    upgrades it to v9 under the ``"population"`` key; a gtg dict (the
    mesh-sharded GTG walk's provenance, algorithms/shapley.GTGShapley
    .post_round) upgrades it to v10 under the ``"gtg"`` key; a
    multihost dict (the distributed shard store's per-host assembly
    summary, parallel/streaming.DistributedCohortStreamer
    .multihost_record) upgrades it to v11 under the ``"multihost"``
    key; a spans dict (the distributed tracing layer's per-round
    per-host summary, telemetry/spans.SpanRecorder.round_summary)
    upgrades it to v12 under the ``"spans"`` key.
    """
    if telemetry is None and client_stats is None and (
        async_federation is None
    ) and stream is None and costmodel is None and valuation is None and (
        sweep is None
    ) and population is None and gtg is None and multihost is None and (
        spans is None
    ):
        return base
    record = dict(base)
    if spans is not None:
        record["schema_version"] = METRICS_SCHEMA_VERSION
    elif multihost is not None:
        record["schema_version"] = _MULTIHOST_SCHEMA_VERSION
    elif gtg is not None:
        record["schema_version"] = _GTG_SCHEMA_VERSION
    elif population is not None:
        record["schema_version"] = _POPULATION_SCHEMA_VERSION
    elif sweep is not None:
        record["schema_version"] = _SWEEP_SCHEMA_VERSION
    elif valuation is not None:
        record["schema_version"] = _VALUATION_SCHEMA_VERSION
    elif costmodel is not None:
        record["schema_version"] = _COSTMODEL_SCHEMA_VERSION
    elif stream is not None:
        record["schema_version"] = _STREAM_SCHEMA_VERSION
    elif async_federation is not None:
        record["schema_version"] = _ASYNC_SCHEMA_VERSION
    elif client_stats is not None:
        record["schema_version"] = _CLIENT_STATS_SCHEMA_VERSION
    else:
        record["schema_version"] = _TELEMETRY_ONLY_SCHEMA_VERSION
    if telemetry is not None:
        record["telemetry"] = telemetry
    if client_stats is not None:
        record["client_stats"] = client_stats
    if async_federation is not None:
        record["async"] = async_federation
    if stream is not None:
        record["stream"] = stream
    if costmodel is not None:
        record["costmodel"] = costmodel
    if valuation is not None:
        record["valuation"] = valuation
    if sweep is not None:
        record["sweep"] = sweep
    if population is not None:
        record["population"] = population
    if gtg is not None:
        record["gtg"] = gtg
    if multihost is not None:
        record["multihost"] = multihost
    if spans is not None:
        record["spans"] = spans
    return record


def config_hash(config) -> str:
    """Short stable hash of the program-defining config fields.

    Stamped into bench output (with :data:`BENCH_SCHEMA_VERSION`) so
    compare_bench.py can refuse to diff runs whose knobs make their
    numbers incomparable. JSON-serialized with sorted keys (repr fallback
    for exotic values) so dict-field ordering can't move the hash.
    """
    d = dataclasses.asdict(config)
    for k in _NON_PROGRAM_FIELDS:
        d.pop(k, None)
    # Off-gated knobs drop out of the hash AT THEIR OFF VALUE: a
    # trace-time-gated feature that is off compiles the exact pre-feature
    # program, so pre-feature configs keep their pre-feature hash
    # (longitudinal bench comparability survives the feature landing)
    # while any ACTIVE setting — which does change the program or its
    # record stream — lands every one of its knobs in the hash.
    if (d.get("client_valuation") or "off").lower() == "off":
        for k in ("client_valuation", "valuation_decay",
                  "valuation_audit_every", "valuation_audit_permutations"):
            d.pop(k, None)
    if not d.get("gtg_cross_round_memo", False):
        d.pop("gtg_cross_round_memo", None)
    if (d.get("span_trace") or "off").lower() == "off":
        # Tracing off IS the pre-feature program (no spans, no journal,
        # no extra DCN arrival stamps), so pre-feature configs keep
        # their pre-feature hash; 'on' perturbs the measured round
        # (instrumentation overhead + the arrival-stamp allgathers) and
        # lands every span knob in the hash.
        for k in ("span_trace", "span_buffer_size", "span_flush_last_k"):
            d.pop(k, None)
    if (d.get("participation_sampler") or "exact").lower() == "exact":
        # 'exact' IS the pre-feature draw (ops/sampling.py), so
        # pre-feature configs keep their pre-feature hash; 'hashed'
        # changes the drawn cohorts and lands in the hash.
        d.pop("participation_sampler", None)
    if not d.get("sweep_seeds") and not d.get("sweep_points"):
        # No sweep requested: the sweep knobs drop out at their off
        # values (pre-feature configs keep their pre-feature hash); an
        # ACTIVE sweep — which changes what the process runs — lands
        # its point list and strategy in the hash.
        for k in ("sweep_seeds", "sweep_points", "sweep_strategy"):
            d.pop(k, None)
    if (d.get("population") or "static").lower() == "static":
        # 'static' IS the pre-feature fixed population (the round
        # program and record stream are untouched), so pre-feature
        # configs keep their pre-feature hash; 'dynamic' changes the
        # program (the departed operand) and the drawn cohorts, and
        # lands every population knob in the hash.
        for k in ("population", "population_seed", "join_rate",
                  "depart_rate", "drift_fraction", "drift_factor"):
            d.pop(k, None)
    blob = json.dumps(d, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def gtg_round_record(history, **extra):
    """The tracked converged-GTG round-cost record (``gtg_round_seconds``),
    shared by bench.py's ``gtg`` sub-object and
    scripts/measure_gtg_scale.py.

    Rounds whose walk never ran (round-truncated:
    ``gtg_permutations == 0``) are not comparable cost points, so the
    record reports the LAST full walk — a converged round is the honest
    cost unit; ``converged`` says whether this one was. Falls back to the
    final round when every round truncated (still inspectable), and
    returns None for an empty history. ``extra`` keys (knobs, peak HBM)
    are merged into the record.
    """
    if not history:
        return None
    walked = [h for h in history if h.get("gtg_permutations")]
    h = walked[-1] if walked else history[-1]
    record = {
        "metric": "gtg_round_seconds",
        "value": round(h["round_seconds"], 1),
        "round": h["round"],
        "converged": bool(h.get("gtg_converged")),
        "permutations": h.get("gtg_permutations"),
        "subset_evals": h.get("gtg_subset_evals"),
    }
    # Subset-eval throughput of the reported round, against the WHOLE
    # round's wall for every mode — a conservative denominator (it
    # includes training + the round eval), but the SAME one whether the
    # walk sharded or not, so a sharded-vs-serial pair (bench's gtg leg
    # flipping BENCH_GTG_DEVICES, measure_gtg_scale's serial reference)
    # compares real end-to-end throughput, never a denominator switch.
    # The walk-window-only rate lives in the v10 ``gtg`` sub-object
    # (``evals_per_s`` there divides by ``walk_seconds``).
    denom = h.get("round_seconds")
    evals = record["subset_evals"]
    record["evals_per_s"] = (
        round(evals / denom, 1) if evals and denom else None
    )
    record.update(extra)
    return record
