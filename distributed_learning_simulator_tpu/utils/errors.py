"""Shared error classification helpers."""

from __future__ import annotations


def is_device_oom(e: Exception) -> bool:
    """True when a JaxRuntimeError is a device RESOURCE_EXHAUSTED OOM.

    THE one copy of the message-form classifier, shared by the
    simulator's round-level ``_oom_hint`` and the Shapley subset
    evaluator's hint — if a jax/XLA upgrade changes the message, one
    fix covers every sized-hint site.
    """
    return "out of memory" in str(e).lower()
