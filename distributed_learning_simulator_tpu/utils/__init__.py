from distributed_learning_simulator_tpu.utils.tree import (
    tree_ravel,
    tree_unravel,
    tree_num_params,
    tree_bytes,
    tree_stack,
    tree_unstack,
    tree_index,
)
from distributed_learning_simulator_tpu.utils.logging import get_logger, set_file_handler

__all__ = [
    "tree_ravel",
    "tree_unravel",
    "tree_num_params",
    "tree_bytes",
    "tree_stack",
    "tree_unstack",
    "tree_index",
    "get_logger",
    "set_file_handler",
]
