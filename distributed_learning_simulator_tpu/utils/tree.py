"""Pytree utilities: the parameter boundary of the framework.

TPU-native replacement for the reference's parameter-dict boundary
(``ModelUtil.get_parameter_dict`` / ``load_parameter_dict``, reference
servers/fed_server.py:6 and workers/fed_worker.py:30,38) and its payload
flatten/size helpers (``concat_dict_values`` / ``load_dict_values`` /
``get_data_serialization_size``, reference servers/fed_quant_server.py:4-6).
In JAX, model parameters already *are* pytrees, so the dict<->tensor boundary
collapses to ravel/unravel, and "serialization size" becomes analytic
dtype-width x numel accounting (see ops/payload.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree


def tree_ravel(tree):
    """Flatten a pytree into a single 1-D vector.

    Returns ``(vector, unravel_fn)``; parity with the reference's
    ``concat_dict_values`` (fed_quant_server.py:4,36) but differentiable and
    jit-compatible.
    """
    return ravel_pytree(tree)


def tree_unravel(unravel_fn, vector):
    """Inverse of :func:`tree_ravel` (reference ``load_dict_values``)."""
    return unravel_fn(vector)


def tree_num_params(tree) -> int:
    """Total number of scalar parameters in the pytree."""
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def tree_bytes(tree, bits_per_element: int | None = None) -> int:
    """Analytic payload size in bytes.

    With ``bits_per_element=None``, uses each leaf's actual dtype width; with
    an override (e.g. 8 for int8 uploads, 1 for sign-SGD), models the size of
    a compressed payload. Replaces the reference's pickle-based
    ``get_data_serialization_size`` (fed_quant_server.py:6,41-48): on TPU
    nothing is serialized, so size is defined analytically.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if bits_per_element is None:
        return sum(x.size * x.dtype.itemsize for x in leaves)
    total_bits = sum(x.size for x in leaves) * bits_per_element
    return (total_bits + 7) // 8


def tree_stack(trees):
    """Stack a list of identically-structured pytrees along a new leading axis.

    This creates the *client axis*: where the reference holds one param dict
    per worker thread (workers/fed_worker.py:30), we hold one pytree whose
    every leaf has leading dim = num_clients.
    """
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def tree_unstack(tree):
    """Split a client-stacked pytree back into a list of per-client pytrees."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    n = leaves[0].shape[0]
    return [
        jax.tree_util.tree_unflatten(treedef, [leaf[i] for leaf in leaves])
        for i in range(n)
    ]


def tree_index(tree, i):
    """Select client ``i``'s slice from a client-stacked pytree."""
    return jax.tree_util.tree_map(lambda x: x[i], tree)
