"""Logging: global logger + per-run file sink.

Parity with the reference's ``get_logger`` / ``set_file_handler`` surface
(reference simulator.py:7,38-46): one framework-global logger, with an optional
file sink at ``log/<algorithm>/<dataset>/<model>/<run-id>.log`` (run id =
seconds_microseconds_pid, unique per run even for same-second starts).
"""

from __future__ import annotations

import logging
import os
import sys
import time

_LOGGER_NAME = "dls_tpu"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def _claim_run_path(log_dir: str, stamp: str) -> str:
    """Atomically claim a unique ``<stamp>[_N].log`` in ``log_dir``.

    ``O_CREAT|O_EXCL`` makes the claim race-free across processes: two
    runs that resolve the same stamp (coarse clocks, forked pids) get
    distinct files instead of interleaving one — the collision that used
    to overwrite logs and interleave metrics.jsonl when two runs started
    within the same second.
    """
    path = os.path.join(log_dir, f"{stamp}.log")
    n = 0
    while True:
        try:
            os.close(os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY))
            return path
        except FileExistsError:
            n += 1
            path = os.path.join(log_dir, f"{stamp}_{n}.log")


def set_file_handler(
    log_root: str,
    algorithm: str,
    dataset: str,
    model: str,
    timestamp: float | None = None,
) -> str:
    """Attach a per-run file sink; returns the log file path.

    Layout parity with reference simulator.py:38-46:
    ``<log_root>/<algorithm>/<dataset>/<model>/<run-id>.log`` — but the
    run id is ``<unix-seconds>_<microseconds>_<pid>`` (plus a counter
    suffix on collision) rather than the reference's bare ``int(ts)``,
    which made two runs starting within the same second overwrite each
    other's log and interleave their ``metrics.jsonl``.
    """
    ts = timestamp if timestamp is not None else time.time()
    log_dir = os.path.join(log_root, algorithm, dataset, model)
    os.makedirs(log_dir, exist_ok=True)
    stamp = f"{int(ts)}_{int((ts % 1) * 1e6):06d}_{os.getpid()}"
    path = _claim_run_path(log_dir, stamp)
    logger = get_logger()
    # One file sink per run: detach the previous run's handler (else a
    # long-lived process fans every later run's lines into all earlier
    # runs' files and leaks descriptors).
    for h in [h for h in logger.handlers if isinstance(h, logging.FileHandler)]:
        logger.removeHandler(h)
        h.close()
    handler = logging.FileHandler(path)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    logger.addHandler(handler)
    return path


def set_run_artifacts(
    log_root: str, algorithm: str, dataset: str, model: str
) -> tuple[str, str]:
    """Attach the per-run file sink and create the per-run artifacts dir.

    Returns ``(log_path, artifacts_dir)``. Single source of the per-run
    layout (``<ts>.log`` + ``<ts>_artifacts/`` with ``metrics.jsonl``,
    Shapley pickles, ...) shared by the vmap and threaded execution paths.
    """
    path = set_file_handler(log_root, algorithm, dataset, model)
    artifacts_dir = path[: -len(".log")] + "_artifacts"
    os.makedirs(artifacts_dir, exist_ok=True)
    return path, artifacts_dir


def set_level(level: str) -> None:
    """Parity with the reference's ``--log_level`` CLI flag (simulator.sh:1)."""
    get_logger().setLevel(getattr(logging, level.upper()))
