"""Logging: global logger + per-run file sink.

Parity with the reference's ``get_logger`` / ``set_file_handler`` surface
(reference simulator.py:7,38-46): one framework-global logger, with an optional
file sink at ``log/<algorithm>/<dataset>/<model>/<timestamp>.log``.
"""

from __future__ import annotations

import logging
import os
import sys
import time

_LOGGER_NAME = "dls_tpu"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
        )
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def set_file_handler(
    log_root: str,
    algorithm: str,
    dataset: str,
    model: str,
    timestamp: float | None = None,
) -> str:
    """Attach a per-run file sink; returns the log file path.

    Layout parity with reference simulator.py:38-46:
    ``<log_root>/<algorithm>/<dataset>/<model>/<timestamp>.log``.
    """
    ts = timestamp if timestamp is not None else time.time()
    log_dir = os.path.join(log_root, algorithm, dataset, model)
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, f"{int(ts)}.log")
    handler = logging.FileHandler(path)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    get_logger().addHandler(handler)
    return path


def set_level(level: str) -> None:
    """Parity with the reference's ``--log_level`` CLI flag (simulator.sh:1)."""
    get_logger().setLevel(getattr(logging, level.upper()))
