"""Checkpoint/resume for (global params, client state, round, algo state).

The reference has NO model-state persistence (SURVEY §5: the only artifact is
the per-round Shapley metric pickle). This module exceeds parity: a round-
granular checkpoint of the full simulation state, so long runs survive
preemption — the failure mode the reference's forever-blocking barrier
(fed_server.py:75-77) cannot.

Format: ``b"DLSC"`` magic + little-endian (crc32: u32, payload_len: u64)
header + a pickle of host (numpy) pytrees — deliberately simple and
orbax-free to stay stable across jax versions; arrays are materialized with
``jax.device_get`` before writing. The CRC recorded at save time is
verified at load (:class:`CheckpointCorruptError` on mismatch/truncation),
and :func:`load_latest_valid_checkpoint` walks back to the newest VALID
checkpoint so a write torn by a crash or disk corruption degrades resume
by one checkpoint interval instead of killing it. Headerless files are
loaded as legacy (pre-CRC) raw pickles.

Writes are atomic (``.tmp`` + ``os.replace``), so a crashed writer can
leave a stale ``*.ckpt.tmp`` behind but never a torn ``*.ckpt`` under
POSIX rename semantics — the CRC exists for everything rename can't
promise (partial flush on power loss, bit rot, truncation in transit).
"""

from __future__ import annotations

import os
import pickle
import re
import struct
import zlib

import jax

from distributed_learning_simulator_tpu.utils.logging import get_logger

_MAGIC = b"DLSC"
_HEADER = struct.Struct("<IQ")  # crc32, payload byte length
# Round-numbered checkpoint files: anything else in checkpoint_dir (a stray
# `foo.ckpt`, editor droppings) is IGNORED by discovery instead of crashing
# the resume sort.
_CKPT_RE = re.compile(r".*_(\d+)\.ckpt$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed integrity verification (truncated header,
    payload length mismatch, CRC mismatch, or an unreadable legacy pickle).
    """


def _write_framed(path: str, payload: dict) -> str:
    """CRC-framed atomic write — the one copy of the DLSC on-disk
    format, shared by whole checkpoints and per-host shards."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(_HEADER.pack(zlib.crc32(blob), len(blob)))
        f.write(blob)
    os.replace(tmp, path)  # atomic: never leaves a torn checkpoint
    return path


def save_checkpoint(path: str, round_idx: int, global_params, client_state,
                    algo_state: dict | None = None, rng_key=None) -> str:
    payload = {
        "round_idx": round_idx,
        "global_params": jax.device_get(global_params),
        "client_state": jax.device_get(client_state),
        "algo_state": algo_state or {},
        "rng_key": None if rng_key is None else jax.device_get(
            jax.random.key_data(rng_key)
        ),
    }
    return _write_framed(path, payload)


def load_checkpoint(path: str) -> dict:
    with open(path, "rb") as f:
        raw = f.read()
    if raw[: len(_MAGIC)] == _MAGIC:
        header_end = len(_MAGIC) + _HEADER.size
        if len(raw) < header_end:
            raise CheckpointCorruptError(
                f"{path}: truncated before the end of the header "
                f"({len(raw)} bytes)"
            )
        crc, length = _HEADER.unpack(raw[len(_MAGIC):header_end])
        blob = raw[header_end:]
        if len(blob) != length:
            raise CheckpointCorruptError(
                f"{path}: payload truncated ({len(blob)} of {length} bytes)"
            )
        if zlib.crc32(blob) != crc:
            raise CheckpointCorruptError(
                f"{path}: CRC mismatch (recorded {crc:#010x}, computed "
                f"{zlib.crc32(blob):#010x})"
            )
        try:
            payload = pickle.loads(blob)
        except Exception as e:
            # CRC-valid but unpicklable (e.g. pickle internals changed by a
            # library upgrade between save and resume): still CORRUPT from
            # the fallback scan's point of view — warn and walk back, don't
            # kill the resume.
            raise CheckpointCorruptError(
                f"{path}: CRC-valid but unpicklable payload ({e})"
            ) from e
    else:
        # Legacy pre-CRC checkpoint: a raw pickle stream. No integrity
        # check is possible; an unreadable one still surfaces as corrupt
        # so the fallback scan can keep walking.
        try:
            payload = pickle.loads(raw)
        except Exception as e:
            raise CheckpointCorruptError(
                f"{path}: unreadable legacy checkpoint ({e})"
            ) from e
    if payload.get("rng_key") is not None:
        payload["rng_key"] = jax.random.wrap_key_data(payload["rng_key"])
    return payload


def checkpoint_rounds(directory: str) -> list[tuple[int, str]]:
    """``(round, path)`` for every round-numbered checkpoint, ascending."""
    if not os.path.isdir(directory):
        return []
    out = []
    for f in os.listdir(directory):
        m = _CKPT_RE.match(f)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, f)))
    out.sort()
    return out


def sweep_stale_tmps(directory: str) -> list[str]:
    """Remove ``*.ckpt.tmp`` files a crashed writer left behind.

    Called at resume time: the single-writer discipline (process 0 writes,
    atomically, one at a time) means any tmp file present when a run
    STARTS is garbage from a previous incarnation. Best-effort — a tmp
    that vanishes mid-sweep is already gone.
    """
    removed = []
    if not os.path.isdir(directory):
        return removed
    for f in os.listdir(directory):
        if f.endswith(".ckpt.tmp"):
            try:
                os.remove(os.path.join(directory, f))
                removed.append(f)
            except OSError:
                pass
    if removed:
        get_logger().info(
            "removed %d stale checkpoint tmp file(s) left by a crashed "
            "writer: %s", len(removed), ", ".join(sorted(removed)),
        )
    return removed


def latest_checkpoint(directory: str) -> str | None:
    """Read-only discovery — deliberately does NOT sweep tmp files (a
    monitoring process may call this while a writer is mid-save; the sweep
    belongs to the resume entry point, before any saves start)."""
    rounds = checkpoint_rounds(directory)
    return rounds[-1][1] if rounds else None


def load_latest_valid_checkpoint(directory: str) -> tuple[str | None, dict | None]:
    """Newest checkpoint that passes integrity verification.

    A corrupt/truncated/unreadable candidate is logged and skipped — a
    torn latest checkpoint costs one checkpoint interval of recomputation
    instead of the whole run. Returns ``(path, payload)`` or
    ``(None, None)`` when nothing valid exists.
    """
    sweep_stale_tmps(directory)
    for _, path in reversed(checkpoint_rounds(directory)):
        try:
            return path, load_checkpoint(path)
        except (CheckpointCorruptError, OSError) as e:
            get_logger().warning(
                "checkpoint %s failed verification (%s); falling back to "
                "the previous checkpoint", path, e,
            )
    return None, None


# --- per-host checkpoint shards + manifest (multihost streamed) -------------
#
# Under ``client_residency='streamed'`` + multihost the store — the
# checkpoint's source of truth — is host-SHARDED (each process owns an
# N/num_hosts client slice, data/residency.DistributedShardStore), so a
# checkpoint becomes: one CRC-framed shard PER HOST (that host's owned
# per-client state slice plus the replicated global state, so every
# shard restores its own process without cross-host reads) and a
# manifest (written by process 0 AFTER every shard landed) recording
# the topology the shards were cut for. Resume validates the manifest
# against the live topology and refuses mismatches with the cause
# named; a round whose manifest never landed (a host died between its
# shard write and the barrier) is invisible to discovery, so resume
# falls back one checkpoint interval — the whole-checkpoint torn-write
# discipline, at shard granularity. Shard/manifest filenames
# deliberately do NOT match ``_CKPT_RE``: legacy single-file discovery
# never sees them, and a single-process resume pointed at a sharded
# directory is refused by the simulator (via :func:`manifest_rounds`)
# instead of silently starting from scratch.

_SHARD_RE = re.compile(r".*_(\d+)\.host(\d+)-of-(\d+)\.ckptshard$")
_MANIFEST_RE = re.compile(r".*_(\d+)\.manifest\.json$")


def shard_checkpoint_path(directory: str, round_idx: int, host_id: int,
                          n_hosts: int) -> str:
    return os.path.join(
        directory, f"round_{round_idx}.host{host_id}-of-{n_hosts}.ckptshard"
    )


def manifest_checkpoint_path(directory: str, round_idx: int) -> str:
    return os.path.join(directory, f"round_{round_idx}.manifest.json")


def save_shard_checkpoint(directory: str, round_idx: int, host_id: int,
                          n_hosts: int, payload: dict,
                          span_recorder=None) -> str:
    """Write this host's checkpoint shard (CRC-framed, atomic).

    ``span_recorder`` (telemetry/spans.SpanRecorder, span_trace='on'):
    the write lands as a per-host ``ckpt_shard_write`` io span — the
    per-host half of the checkpoint-barrier skew story (a slow disk here
    shows up as the OTHER hosts' ``ckpt_barrier_wait``)."""
    payload = dict(payload)
    payload["round_idx"] = round_idx
    payload["host_id"] = host_id
    payload["n_hosts"] = n_hosts
    path = shard_checkpoint_path(directory, round_idx, host_id, n_hosts)
    if span_recorder is None:
        return _write_framed(path, payload)
    with span_recorder.span(
        "ckpt_shard_write", "io", round_idx=round_idx
    ) as sp:
        out = _write_framed(path, payload)
        try:
            sp["bytes"] = os.path.getsize(out)
        except OSError:
            pass
    return out


def write_manifest(directory: str, round_idx: int, manifest: dict,
                   span_recorder=None) -> str:
    """Write the round's manifest (process 0, after the shard barrier).

    Atomic like the shards; its EXISTENCE is the round's commit record —
    discovery only offers rounds whose manifest landed. The optional
    ``span_recorder`` journals the commit as a ``ckpt_manifest`` io
    span."""
    import json

    manifest = dict(manifest)
    manifest["round"] = round_idx
    path = manifest_checkpoint_path(directory, round_idx)

    def _write() -> str:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, sort_keys=True)
        os.replace(tmp, path)
        return path

    if span_recorder is None:
        return _write()
    with span_recorder.span("ckpt_manifest", "io", round_idx=round_idx):
        return _write()


def manifest_rounds(directory: str) -> list[tuple[int, str]]:
    """``(round, manifest_path)`` for every sharded checkpoint round,
    ascending. Empty for non-sharded (or absent) directories."""
    if not os.path.isdir(directory):
        return []
    out = []
    for f in os.listdir(directory):
        m = _MANIFEST_RE.match(f)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, f)))
    out.sort()
    return out


def validate_manifest(manifest: dict, *, n_hosts: int, n_clients: int,
                      owner_bounds=None) -> None:
    """Refuse a manifest cut for a different topology, naming the cause.

    The shards slice client state by (host count, ownership bounds);
    restoring them into a differently-split run would silently hand
    clients to the wrong owners — exactly the class of quiet corruption
    the cause-named-refusal discipline exists to prevent.
    """
    if int(manifest.get("n_hosts", -1)) != n_hosts:
        raise RuntimeError(
            "multihost checkpoint topology mismatch: manifest was "
            f"written by {manifest.get('n_hosts')} host process(es) but "
            f"this run has {n_hosts}; resume with the host count the "
            "checkpoint was written with (per-host shards cannot be "
            "re-split)"
        )
    if int(manifest.get("n_clients", -1)) != n_clients:
        raise RuntimeError(
            "multihost checkpoint population mismatch: manifest covers "
            f"{manifest.get('n_clients')} clients but this run has "
            f"{n_clients}; resume with the configuration the checkpoint "
            "was written with"
        )
    if owner_bounds is not None:
        want = [int(b) for b in owner_bounds]
        got = [int(b) for b in manifest.get("owner_bounds", [])]
        if want != got:
            raise RuntimeError(
                "multihost checkpoint ownership mismatch: manifest "
                f"bounds {got} != this run's {want} (the mesh's "
                "per-host device split changed); resume on the "
                "topology the checkpoint was written with"
            )


def load_latest_valid_sharded_checkpoint(
    directory: str, host_id: int, n_hosts: int,
) -> tuple[dict | None, dict | None]:
    """Newest sharded checkpoint whose manifest landed, every shard file
    exists, and THIS host's shard passes CRC verification.

    Returns ``(manifest, shard_payload)`` or ``(None, None)``. A
    candidate failing an INTEGRITY check (unreadable manifest, missing
    shard file, CRC mismatch) is logged and skipped — the
    one-interval-degradation contract of
    :func:`load_latest_valid_checkpoint`, at shard granularity. A
    manifest whose host count differs from this run's is a TOPOLOGY
    refusal, raised immediately (never walked past — see the inline
    comment). Cross-host agreement on WHICH round every process
    restored is the simulator's job (its existing allgather check
    covers it).
    """
    import json

    sweep_stale_tmps(directory)
    for round_idx, mpath in reversed(manifest_rounds(directory)):
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, ValueError) as e:
            get_logger().warning(
                "checkpoint manifest %s unreadable (%s); falling back",
                mpath, e,
            )
            continue
        if int(manifest.get("n_hosts", -1)) != n_hosts:
            # A host-count change is a topology REFUSAL, not corruption:
            # this host's shard path is derived from the CURRENT
            # (host_id, n_hosts), so without this check a resume under a
            # different host count would find no shard, skip every
            # round as "invalid", and silently restart from scratch —
            # exactly the quiet data loss the cause-named-refusal
            # discipline forbids. Raised here (not only in
            # validate_manifest, which the simulator calls after a
            # successful load) so the walk-back loop can never step
            # past it.
            raise RuntimeError(
                "multihost checkpoint topology mismatch: manifest "
                f"{os.path.basename(mpath)} was written by "
                f"{manifest.get('n_hosts')} host process(es) but this "
                f"run has {n_hosts}; resume with the host count the "
                "checkpoint was written with (per-host shards cannot "
                "be re-split)"
            )
        shard_files = manifest.get("shards") or [
            os.path.basename(
                shard_checkpoint_path(directory, round_idx, h,
                                      int(manifest.get("n_hosts", 0)))
            )
            for h in range(int(manifest.get("n_hosts", 0)))
        ]
        missing = [
            s for s in shard_files
            if not os.path.exists(os.path.join(directory, s))
        ]
        if missing:
            get_logger().warning(
                "sharded checkpoint round %d is missing shard(s) %s; "
                "falling back to the previous checkpoint",
                round_idx, ", ".join(missing),
            )
            continue
        my_path = shard_checkpoint_path(directory, round_idx, host_id,
                                        n_hosts)
        try:
            payload = load_checkpoint(my_path)
        except (CheckpointCorruptError, OSError) as e:
            get_logger().warning(
                "checkpoint shard %s failed verification (%s); falling "
                "back to the previous checkpoint", my_path, e,
            )
            continue
        return manifest, payload
    return None, None


def gc_sharded_checkpoints(directory: str,
                           keep_last: int | None) -> list[str]:
    """Retention for sharded checkpoints: keep the newest ``keep_last``
    MANIFEST rounds; older rounds lose their manifest and every shard."""
    if not keep_last or keep_last < 1:
        return []
    removed = []
    drop_rounds = [r for r, _ in manifest_rounds(directory)[:-keep_last]]
    if not drop_rounds:
        return removed
    drop = set(drop_rounds)
    for f in os.listdir(directory):
        m = _SHARD_RE.match(f) or _MANIFEST_RE.match(f)
        if m and int(m.group(1)) in drop:
            try:
                os.remove(os.path.join(directory, f))
                removed.append(os.path.join(directory, f))
            except OSError:
                pass
    return removed


def gc_checkpoints(directory: str, keep_last: int | None) -> list[str]:
    """Delete all but the newest ``keep_last`` round-numbered checkpoints
    (``config.checkpoint_keep_last``; None = keep everything). Runs after
    each successful save so week-long chaos/preemption runs don't fill the
    disk. Best-effort removals; returns the deleted paths."""
    if not keep_last or keep_last < 1:
        return []
    removed = []
    for _, path in checkpoint_rounds(directory)[:-keep_last]:
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed
