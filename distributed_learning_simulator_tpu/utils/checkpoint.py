"""Checkpoint/resume for (global params, client state, round, algo state).

The reference has NO model-state persistence (SURVEY §5: the only artifact is
the per-round Shapley metric pickle). This module exceeds parity: a round-
granular checkpoint of the full simulation state, so long runs survive
preemption — the failure mode the reference's forever-blocking barrier
(fed_server.py:75-77) cannot.

Format: a pickle of host (numpy) pytrees — deliberately simple and
orbax-free to stay stable across jax versions; arrays are materialized with
``jax.device_get`` before writing.
"""

from __future__ import annotations

import os
import pickle

import jax


def save_checkpoint(path: str, round_idx: int, global_params, client_state,
                    algo_state: dict | None = None, rng_key=None) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {
        "round_idx": round_idx,
        "global_params": jax.device_get(global_params),
        "client_state": jax.device_get(client_state),
        "algo_state": algo_state or {},
        "rng_key": None if rng_key is None else jax.device_get(
            jax.random.key_data(rng_key)
        ),
    }
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f)
    os.replace(tmp, path)  # atomic: never leaves a torn checkpoint
    return path


def load_checkpoint(path: str) -> dict:
    with open(path, "rb") as f:
        payload = pickle.load(f)
    if payload.get("rng_key") is not None:
        payload["rng_key"] = jax.random.wrap_key_data(payload["rng_key"])
    return payload


def latest_checkpoint(directory: str) -> str | None:
    if not os.path.isdir(directory):
        return None
    ckpts = [f for f in os.listdir(directory) if f.endswith(".ckpt")]
    if not ckpts:
        return None
    ckpts.sort(key=lambda f: int(f.split("_")[-1].split(".")[0]))
    return os.path.join(directory, ckpts[-1])
