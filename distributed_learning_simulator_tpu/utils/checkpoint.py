"""Checkpoint/resume for (global params, client state, round, algo state).

The reference has NO model-state persistence (SURVEY §5: the only artifact is
the per-round Shapley metric pickle). This module exceeds parity: a round-
granular checkpoint of the full simulation state, so long runs survive
preemption — the failure mode the reference's forever-blocking barrier
(fed_server.py:75-77) cannot.

Format: ``b"DLSC"`` magic + little-endian (crc32: u32, payload_len: u64)
header + a pickle of host (numpy) pytrees — deliberately simple and
orbax-free to stay stable across jax versions; arrays are materialized with
``jax.device_get`` before writing. The CRC recorded at save time is
verified at load (:class:`CheckpointCorruptError` on mismatch/truncation),
and :func:`load_latest_valid_checkpoint` walks back to the newest VALID
checkpoint so a write torn by a crash or disk corruption degrades resume
by one checkpoint interval instead of killing it. Headerless files are
loaded as legacy (pre-CRC) raw pickles.

Writes are atomic (``.tmp`` + ``os.replace``), so a crashed writer can
leave a stale ``*.ckpt.tmp`` behind but never a torn ``*.ckpt`` under
POSIX rename semantics — the CRC exists for everything rename can't
promise (partial flush on power loss, bit rot, truncation in transit).
"""

from __future__ import annotations

import os
import pickle
import re
import struct
import zlib

import jax

from distributed_learning_simulator_tpu.utils.logging import get_logger

_MAGIC = b"DLSC"
_HEADER = struct.Struct("<IQ")  # crc32, payload byte length
# Round-numbered checkpoint files: anything else in checkpoint_dir (a stray
# `foo.ckpt`, editor droppings) is IGNORED by discovery instead of crashing
# the resume sort.
_CKPT_RE = re.compile(r".*_(\d+)\.ckpt$")


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed integrity verification (truncated header,
    payload length mismatch, CRC mismatch, or an unreadable legacy pickle).
    """


def save_checkpoint(path: str, round_idx: int, global_params, client_state,
                    algo_state: dict | None = None, rng_key=None) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {
        "round_idx": round_idx,
        "global_params": jax.device_get(global_params),
        "client_state": jax.device_get(client_state),
        "algo_state": algo_state or {},
        "rng_key": None if rng_key is None else jax.device_get(
            jax.random.key_data(rng_key)
        ),
    }
    blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_MAGIC)
        f.write(_HEADER.pack(zlib.crc32(blob), len(blob)))
        f.write(blob)
    os.replace(tmp, path)  # atomic: never leaves a torn checkpoint
    return path


def load_checkpoint(path: str) -> dict:
    with open(path, "rb") as f:
        raw = f.read()
    if raw[: len(_MAGIC)] == _MAGIC:
        header_end = len(_MAGIC) + _HEADER.size
        if len(raw) < header_end:
            raise CheckpointCorruptError(
                f"{path}: truncated before the end of the header "
                f"({len(raw)} bytes)"
            )
        crc, length = _HEADER.unpack(raw[len(_MAGIC):header_end])
        blob = raw[header_end:]
        if len(blob) != length:
            raise CheckpointCorruptError(
                f"{path}: payload truncated ({len(blob)} of {length} bytes)"
            )
        if zlib.crc32(blob) != crc:
            raise CheckpointCorruptError(
                f"{path}: CRC mismatch (recorded {crc:#010x}, computed "
                f"{zlib.crc32(blob):#010x})"
            )
        try:
            payload = pickle.loads(blob)
        except Exception as e:
            # CRC-valid but unpicklable (e.g. pickle internals changed by a
            # library upgrade between save and resume): still CORRUPT from
            # the fallback scan's point of view — warn and walk back, don't
            # kill the resume.
            raise CheckpointCorruptError(
                f"{path}: CRC-valid but unpicklable payload ({e})"
            ) from e
    else:
        # Legacy pre-CRC checkpoint: a raw pickle stream. No integrity
        # check is possible; an unreadable one still surfaces as corrupt
        # so the fallback scan can keep walking.
        try:
            payload = pickle.loads(raw)
        except Exception as e:
            raise CheckpointCorruptError(
                f"{path}: unreadable legacy checkpoint ({e})"
            ) from e
    if payload.get("rng_key") is not None:
        payload["rng_key"] = jax.random.wrap_key_data(payload["rng_key"])
    return payload


def checkpoint_rounds(directory: str) -> list[tuple[int, str]]:
    """``(round, path)`` for every round-numbered checkpoint, ascending."""
    if not os.path.isdir(directory):
        return []
    out = []
    for f in os.listdir(directory):
        m = _CKPT_RE.match(f)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, f)))
    out.sort()
    return out


def sweep_stale_tmps(directory: str) -> list[str]:
    """Remove ``*.ckpt.tmp`` files a crashed writer left behind.

    Called at resume time: the single-writer discipline (process 0 writes,
    atomically, one at a time) means any tmp file present when a run
    STARTS is garbage from a previous incarnation. Best-effort — a tmp
    that vanishes mid-sweep is already gone.
    """
    removed = []
    if not os.path.isdir(directory):
        return removed
    for f in os.listdir(directory):
        if f.endswith(".ckpt.tmp"):
            try:
                os.remove(os.path.join(directory, f))
                removed.append(f)
            except OSError:
                pass
    if removed:
        get_logger().info(
            "removed %d stale checkpoint tmp file(s) left by a crashed "
            "writer: %s", len(removed), ", ".join(sorted(removed)),
        )
    return removed


def latest_checkpoint(directory: str) -> str | None:
    """Read-only discovery — deliberately does NOT sweep tmp files (a
    monitoring process may call this while a writer is mid-save; the sweep
    belongs to the resume entry point, before any saves start)."""
    rounds = checkpoint_rounds(directory)
    return rounds[-1][1] if rounds else None


def load_latest_valid_checkpoint(directory: str) -> tuple[str | None, dict | None]:
    """Newest checkpoint that passes integrity verification.

    A corrupt/truncated/unreadable candidate is logged and skipped — a
    torn latest checkpoint costs one checkpoint interval of recomputation
    instead of the whole run. Returns ``(path, payload)`` or
    ``(None, None)`` when nothing valid exists.
    """
    sweep_stale_tmps(directory)
    for _, path in reversed(checkpoint_rounds(directory)):
        try:
            return path, load_checkpoint(path)
        except (CheckpointCorruptError, OSError) as e:
            get_logger().warning(
                "checkpoint %s failed verification (%s); falling back to "
                "the previous checkpoint", path, e,
            )
    return None, None


def gc_checkpoints(directory: str, keep_last: int | None) -> list[str]:
    """Delete all but the newest ``keep_last`` round-numbered checkpoints
    (``config.checkpoint_keep_last``; None = keep everything). Runs after
    each successful save so week-long chaos/preemption runs don't fill the
    disk. Best-effort removals; returns the deleted paths."""
    if not keep_last or keep_last < 1:
        return []
    removed = []
    for _, path in checkpoint_rounds(directory)[:-keep_last]:
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed
