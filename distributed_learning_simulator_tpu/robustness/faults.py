"""Per-round client failure model, applied INSIDE the jitted round.

FedJAX (arXiv:2108.02117) treats client failure simulation as a
first-class framework primitive; the reference simulator instead deadlocks
on the first client that never reports (fed_server.py:75-77). This module
is the injectable attack surface for the repo's existing defenses
(ops/aggregate.py robust rules, the host loop's quorum policy): a
:class:`FailureModel` built from config draws a per-client failure mask
from the ROUND key every round — no retrace across rounds, replicated
(hence consistent) under mesh sharding, and resume-deterministic because
the round key chain is checkpointed.

Failure modes (``config.failure_mode``):

  * ``dropout`` — the client never trains this round: its update is
    excluded from aggregation (weight 0, survivors renormalized) and its
    persistent per-client state is frozen.
  * ``straggler`` — the client trains but its upload arrives after the
    round closes. In a synchronous run (``async_mode='off'``, the
    pinned default) the update is excluded like dropout — the server
    can only wait or drop — but its local state advances (it did the
    work; only the server missed it). With the arrival model on
    (``async_mode='on'``, robustness/arrivals.py) the same fault means
    "arrives after the deadline": the upload is routed into the
    staleness buffer at a forced staleness of at least 1 and applied in
    a later round, and the client counts as a survivor — graceful
    degradation replacing wait-or-drop.
  * ``corrupt_nan`` — the client reports on time but its upload is
    garbage: every parameter is NaN. Keeps its aggregation weight (the
    server cannot know the payload is poison before aggregating).
  * ``corrupt_scale`` — finite Byzantine garbage: the upload is the true
    update scaled by :data:`CORRUPT_SCALE` (a large-norm attack that NaN
    guards cannot see but median/trimmed-mean/krum must absorb).

``failure_correlation`` models round-correlated outages (a rack power
event takes out many clients at once): each client's uniform draw is
replaced, with probability ``correlation``, by one draw SHARED across the
round's cohort — the marginal per-client failure rate stays exactly
``failure_prob`` while failures cluster into bad rounds; ``1.0`` makes
every round all-or-nothing.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

#: Multiplier a ``corrupt_scale`` client applies to its upload. Large
#: enough that an unweighted mean over a reference-sized cohort moves by
#: an order of magnitude (the attack is visible), small enough to stay
#: finite in f32 through any downstream payload transform.
CORRUPT_SCALE = 100.0

MODES = ("none", "dropout", "straggler", "corrupt_nan", "corrupt_scale")


def all_finite(tree):
    """Scalar bool: every leaf of ``tree`` is finite (shared by the robust
    aggregation guard and the quorum policy in fedavg/sign_sgd)."""
    return jnp.all(jnp.stack([
        jnp.all(jnp.isfinite(leaf.astype(jnp.float32)))
        for leaf in jax.tree_util.tree_leaves(tree)
    ]))


@dataclass(frozen=True)
class FailureModel:
    """Static (trace-time) failure configuration; per-round draws are pure
    functions of the round key, so one compiled round program serves every
    round."""

    mode: str
    prob: float
    correlation: float = 0.0
    seed: int = 0

    @classmethod
    def from_config(cls, config) -> "FailureModel | None":
        """None when no failure model is active (``mode='none'`` or
        ``prob<=0``) — callers gate every trace-time branch on that, so
        failure-free runs compile the exact pre-feature program."""
        mode = getattr(config, "failure_mode", "none") or "none"
        prob = float(getattr(config, "failure_prob", 0.0))
        if mode == "none" or prob <= 0.0:
            return None
        if mode not in MODES:
            raise ValueError(
                f"unknown failure_mode {mode!r}; known: {', '.join(MODES)}"
            )
        return cls(
            mode=mode,
            prob=prob,
            correlation=float(getattr(config, "failure_correlation", 0.0)),
            seed=int(getattr(config, "failure_seed", 0)),
        )

    # ---- mode semantics (trace-time predicates) ---------------------------
    @property
    def excludes_update(self) -> bool:
        """Failed client contributes nothing to aggregation (weight 0);
        survivors are renormalized over the remaining weight."""
        return self.mode in ("dropout", "straggler")

    @property
    def routes_to_buffer(self) -> bool:
        """Whether an active ASYNC round (robustness/arrivals.py) should
        treat this failure as a late-but-arriving upload — forced past
        the deadline into the staleness buffer — instead of excluding
        it. Only ``straggler`` qualifies: its upload exists and arrives;
        dropout never trained and the corrupt modes damage the payload,
        not its timing. Consulted only when an AsyncFederation is
        active, so synchronous semantics stay byte-identical."""
        return self.mode == "straggler"

    @property
    def corrupts_upload(self) -> bool:
        """Failed client reports garbage WITH its full aggregation weight."""
        return self.mode in ("corrupt_nan", "corrupt_scale")

    @property
    def freezes_client_state(self) -> bool:
        """Dropout never ran locally, so persistent per-client state
        (momentum buffers, non-reset optimizers) must not advance; a
        straggler trained — only its upload was lost."""
        return self.mode == "dropout"

    # ---- jit-side draws ----------------------------------------------------
    def draw_failed(self, key, n: int):
        """Bool ``[n]`` failure mask for one round's cohort.

        ``fold_in(key, seed)`` decouples the failure stream from every
        other consumer of the round key: changing ``failure_seed`` re-rolls
        WHICH clients fail without touching cohort sampling, training
        batches, or payload keys (and vice versa).
        """
        k = jax.random.fold_in(key, self.seed)
        k_common, k_ind, k_mix = jax.random.split(k, 3)
        u_ind = jax.random.uniform(k_ind, (n,))
        if self.correlation > 0.0:
            u_common = jax.random.uniform(k_common, ())
            use_common = jax.random.uniform(k_mix, (n,)) < self.correlation
            u = jnp.where(use_common, u_common, u_ind)
        else:
            u = u_ind
        return u < self.prob

    def corrupt_stack(self, stacked_tree, failed):
        """Apply the corrupt-mode payload damage to a client-stacked pytree
        (leading axis = clients). Applied to the RAW upload, before any
        payload transform (quantization happens client-side too, so a
        faulty client quantizes its own garbage)."""
        def _leaf(x):
            f = failed.reshape((-1,) + (1,) * (x.ndim - 1))
            if self.mode == "corrupt_nan":
                bad = jnp.full_like(x, jnp.nan)
            else:
                bad = x * jnp.asarray(CORRUPT_SCALE, x.dtype)
            return jnp.where(f, bad, x)

        return jax.tree_util.tree_map(_leaf, stacked_tree)

    def freeze_failed_state(self, failed, old_state, new_state):
        """Per-client persistent state for failed clients reverts to its
        round-start value (dropout semantics); no-op for stateless runs."""
        if old_state is None or new_state is None:
            return new_state

        def _leaf(old, new):
            f = failed.reshape((-1,) + (1,) * (new.ndim - 1))
            return jnp.where(f, old, new)

        return jax.tree_util.tree_map(_leaf, old_state, new_state)
