"""Open-world client populations: joins, departures, drift.

Every robustness layer before this one — fault injection (faults.py),
async arrivals (arrivals.py), chaos resume (chaos.py) — assumed the
static client population every simulator framework bakes in. Real
federated deployments are open-world: devices register, disappear, and
change quality mid-run (FedML Parrot motivates exactly this
client-behavior realism at scale). ``config.population='dynamic'``
drives that scenario from a **round-key-chained registration stream**:

* **joins** (``join_rate``) — new clients register per round; their data
  shards are drawn over a growing index space (IID draws from the
  training set at the packed slot size, keyed by the stream) and
  appended to the host shard store (data/residency.HostShardStore.grow —
  the hashed sampler draws from an *index space*, so growing N needs no
  O(N) state anywhere). A joiner becomes sampleable from the NEXT round.
* **departures** (``depart_rate``) — each alive client departs with a
  per-round probability; departed indices are masked out of the hashed
  sampler's first-k-distinct stream (ops/sampling.py ``alive``) and
  never resampled. A departure that hits a client sampled in the SAME
  round zeroes its contribution in-program (the ``departed`` operand,
  algorithms/fedavg.py) — and when survivors then fall below
  ``min_survivors`` the round is rejected in-program with the previous
  global retained, exactly the PR 2 quorum contract. Departures are
  capped so the alive population never falls below the pinned cohort
  size (the sampler must still fill a cohort); dropped draws are
  deterministic (client-index order).
* **drift** (``drift_fraction``/``drift_factor``) — a planted cohort of
  the STARTUP population whose data quality degrades on a schedule:
  member of rank j (of m) ramps linearly over the run toward
  ``drift_factor * (j+1)/m`` of its labels re-labeled uniformly at
  random. Corruption is *absolute per round* (a fixed per-client slot
  order + noise labels, the first k(round) slots corrupted), so applying
  it lazily — only to sampled drifting clients, right before their slice
  is gathered — is idempotent and resume-exact without checkpointing any
  drift state. The graded cohort is the engineered ground truth the
  PR 9 streaming valuation is measured against (tests/test_population.py
  pins Spearman >= 0.8 against the planted grades).

**Determinism.** All three event streams derive from
``fold_in(round_key, _POP_FOLD + population_seed)`` — the PR 2/6 fold_in
discipline: activating (or re-seeding) the registration stream re-rolls
nothing else, and every event is a pure function of the checkpointed
round-key chain. The stream *state* (alive mask, registered count,
joined shards — drawn from past round keys a resumed run cannot replay)
is checkpointed (:meth:`PopulationModel.checkpoint_state`) and restored
(:meth:`PopulationModel.restore`), so a resume mid-growth stitches
bit-identically (tests/test_chaos_resume.py's mid-growth variant).

The per-round cohort stays pinned at the startup population's sampled
size, so the compiled round program never changes shape while N grows —
what makes a 10x growth run cost ~a static run (bench.py's ``churn``
leg gates the overhead). Composition matrix and refusal causes:
config.validate() + docs/ROBUSTNESS.md § Dynamic populations.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from distributed_learning_simulator_tpu.data.partition import (
    _compact_encode,
)
from distributed_learning_simulator_tpu.ops.sampling import threefry2x32

#: fold_in salt decoupling the registration stream from every other
#: round-key consumer (failure_seed / arrival_seed use the same
#: discipline with their own constants).
_POP_FOLD = 104729

#: Counter-lane tags separating the three event streams drawn from one
#: round's fold_in words (the x1 word of the Threefry counter).
_LANE_DEPART = 1
_LANE_JOIN = 2
_LANE_SHARD = 3

#: Drift-cohort member ids are listed in per-round records only up to
#: this size (the PER_CLIENT_CAP discipline — large cohorts report their
#: size, never a list that bloats metrics.jsonl).
DRIFT_IDS_CAP = 32

#: One jitted ``round_key -> key_data(fold_in(round_key, salt))`` chain
#: per population_seed — the fedavg._hashed_part_key_words discipline:
#: the derivation runs once per round, and composing fold_in + key_data
#: eagerly costs ~10 ms of per-op dispatch, 50x the whole event draw.
_POP_WORDS_JIT: dict = {}


def pop_key_words(round_key, seed: int) -> np.ndarray:
    """The uint32 key words of a round's registration stream:
    ``key_data(fold_in(round_key, _POP_FOLD + seed))`` — the ONE
    derivation (simulator + tests), compiled once per seed."""
    import jax

    fn = _POP_WORDS_JIT.get(seed)
    if fn is None:
        def _words(key, _salt=_POP_FOLD + seed):
            return jax.random.key_data(jax.random.fold_in(key, _salt))

        fn = jax.jit(_words)
        _POP_WORDS_JIT[seed] = fn
    return np.asarray(fn(round_key)).ravel()


def _stream_uniform(words, lane: int, start: int, size: int) -> np.ndarray:
    """``size`` uniform [0, 1) draws from the round's registration-stream
    words at counter positions ``start..start+size-1`` of ``lane`` —
    pure numpy (ops/sampling.threefry2x32 with xp=np), so the stream is
    unit-testable without a backend and identical on every host."""
    kw = np.asarray(words).ravel()
    ctr = np.arange(start, start + size, dtype=np.uint32)
    v0, _ = threefry2x32(
        np, np.uint32(kw[0]), np.uint32(kw[1]), ctr,
        np.full(size, lane, np.uint32),
    )
    return v0.astype(np.float64) / 2.0**32


def _stream_ints(words, lane: int, size: int, n: int) -> np.ndarray:
    """``size`` stream integers in [0, n) (shard sample draws — the
    ~n/2^32 modulo bias is statistically irrelevant for data sampling,
    unlike the cohort draw's exactly-uniform contract)."""
    kw = np.asarray(words).ravel()
    ctr = np.arange(size, dtype=np.uint32)
    v0, _ = threefry2x32(
        np, np.uint32(kw[0]), np.uint32(kw[1]), ctr,
        np.full(size, lane, np.uint32),
    )
    return (v0 % np.uint32(n)).astype(np.int64)


@dataclass
class PopulationEvents:
    """One round's registration-stream outcome (drawn BEFORE the round's
    dispatch — the departure mask is a round-program operand — and
    APPLIED after it: a joiner is sampleable from the next round)."""

    round_idx: int
    joins: int
    departs: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.int64)
    )


class PopulationModel:
    """The dynamic population's host-side owner (see module docstring).

    State: the ``alive`` bool mask over the registered index space and
    ``n_registered`` (the index space's current size; the store's
    client-axis length tracks it). The model never touches device state
    — the streamed cohort pipeline is population-size-free by
    construction, which is the whole design.
    """

    @classmethod
    def from_config(cls, config, n_initial: int, cohort: int,
                    dataset=None) -> "PopulationModel | None":
        """None when ``population='static'`` — every call site gates on
        that, so static runs execute the exact pre-feature path."""
        mode = (getattr(config, "population", "static") or "static").lower()
        if mode == "static":
            return None
        return cls(config, n_initial, cohort, dataset=dataset)

    def __init__(self, config, n_initial: int, cohort: int, dataset=None):
        self.config = config
        self.n0 = int(n_initial)
        self.cohort = int(cohort)
        self.seed = int(getattr(config, "population_seed", 0))
        self.join_rate = float(getattr(config, "join_rate", 0.0))
        self.depart_rate = float(getattr(config, "depart_rate", 0.0))
        self.total_rounds = int(getattr(config, "round", 1))
        self.alive = np.ones(self.n0, dtype=bool)
        self.n_registered = self.n0
        self.totals = {"joins": 0, "departs": 0}
        # Round whose events were last APPLIED — the registration-stream
        # cursor the checkpoint carries (resume must not re-apply or
        # skip a round's events).
        self.cursor = -1
        # Join-shard source: the training set the growing index space
        # draws from (None = joins refuse; tests exercising only
        # departures/drift may omit the dataset).
        self._x_train = None
        self._y_train = None
        self._num_classes = None
        if dataset is not None:
            self._x_train = np.asarray(dataset.x_train)
            self._y_train = np.asarray(dataset.y_train)
            self._num_classes = int(dataset.num_classes)
        # ---- planted drift cohort (startup population only) ----------------
        m = int(round(float(getattr(config, "drift_fraction", 0.0))
                      * self.n0))
        factor = float(getattr(config, "drift_factor", 0.5))
        rng = np.random.default_rng(self.seed + 9973)
        self.drift_ids = (
            np.sort(rng.choice(self.n0, size=m, replace=False))
            if m > 0 else np.zeros(0, np.int64)
        )
        order = rng.permutation(m)
        #: grade[i] = peak corruption fraction of drift_ids[i] — a
        #: monotone gradient over the (shuffled) cohort, the planted
        #: ground truth valuation is correlated against.
        self.drift_grades = (
            factor * (order + 1.0) / m if m > 0 else np.zeros(0)
        )
        # Per-member corruption pack, built lazily from the store rows
        # the first time the member is sampled: (original y row, valid-
        # slot corruption order, fixed noise labels).
        self._drift_pack: dict[int, tuple] = {}
        self._drift_index = {
            int(c): i for i, c in enumerate(self.drift_ids)
        }

    # ---- event stream -------------------------------------------------------
    def draw_events(self, words, round_idx: int) -> PopulationEvents:
        """Round ``round_idx``'s registration events from its fold_in
        words (``jax.random.fold_in(round_key, _POP_FOLD + seed)`` key
        data — the simulator derives them once per round). Pure: the
        model's state is only changed by :meth:`apply`."""
        joins = 0
        if self.join_rate > 0.0:
            base = int(self.join_rate)
            frac = self.join_rate - base
            joins = base
            if frac > 0.0 and _stream_uniform(words, _LANE_JOIN, 0, 1)[0] < (
                frac
            ):
                joins += 1
        departs = np.zeros(0, np.int64)
        if self.depart_rate > 0.0:
            # Keyed by TRUE client index (counter = id): a client's
            # departure draw is stable under any array packing.
            u = _stream_uniform(words, _LANE_DEPART, 0, self.n_registered)
            cand = np.flatnonzero(self.alive & (u < self.depart_rate))
            # Cap: the alive population must keep at least a cohort's
            # worth of clients (the sampler has to fill k slots). Joins
            # land after this round's draw, so the cap ignores them;
            # excess draws are dropped in index order — deterministic.
            allowed = max(0, int(self.alive.sum()) - self.cohort)
            departs = cand[:allowed].astype(np.int64)
        return PopulationEvents(
            round_idx=round_idx, joins=joins, departs=departs
        )

    def cohort_departed_mask(self, events: PopulationEvents,
                            cohort_ids) -> np.ndarray:
        """Bool mask over the round's sampled cohort: which members
        depart THIS round (the round program's ``departed`` operand —
        their contribution is zeroed in-program, quorum-visible)."""
        return np.isin(np.asarray(cohort_ids), events.departs)

    # ---- join shards --------------------------------------------------------
    def _join_rows(self, store, events: PopulationEvents, words):
        """Packed shard rows for this round's joiners: IID draws from
        the training set at the store's slot size, keyed by the
        registration stream — 'the partitioner over a growing index
        space'. Matches the store layout (compact uint8 or float32)."""
        if self._x_train is None:
            raise ValueError(
                "population='dynamic' with join_rate > 0 needs the "
                "dataset (the growing index space draws joiners' shards "
                "from the training set); run through run_simulation or "
                "pass dataset= to PopulationModel"
            )
        n_new = events.joins
        slots = store.x.shape[1]
        idx = _stream_ints(words, _LANE_SHARD, n_new * slots,
                           self._x_train.shape[0])
        xs = self._x_train[idx]
        if store.x.dtype == np.uint8:
            dim = store.x.shape[2]
            x_rows = _compact_encode(
                xs.reshape(n_new * slots, -1).astype(np.float32),
                n_new * slots, dim,
            ).reshape(n_new, slots, dim)
        else:
            x_rows = xs.astype(store.x.dtype).reshape(
                (n_new, slots) + store.x.shape[2:]
            )
        y_rows = self._y_train[idx].astype(np.int32).reshape(n_new, slots)
        mask_rows = np.ones((n_new, slots), dtype=np.float32)
        sizes_rows = np.full(n_new, float(slots), dtype=np.float32)
        return x_rows, y_rows, mask_rows, sizes_rows

    # ---- state transitions --------------------------------------------------
    def apply(self, events: PopulationEvents, store,
              state_proto=None, words=None) -> None:
        """Apply one round's events to the population state + store:
        joins append (sampleable from the NEXT round), departures clear
        the alive mask (never resampled). ``state_proto`` is a one-row
        per-client state tree (None for stateless algorithms) replicated
        per joiner."""
        if events.joins > 0:
            x_r, y_r, m_r, s_r = self._join_rows(store, events, words)
            state_rows = None
            if store.state is not None:
                from distributed_learning_simulator_tpu.data.residency import (
                    tree_map_np,
                )

                if state_proto is None:
                    raise ValueError(
                        "store carries per-client state; joins need a "
                        "state_proto row"
                    )
                state_rows = tree_map_np(
                    lambda a: np.repeat(
                        np.asarray(a), events.joins, axis=0
                    ),
                    state_proto,
                )
            store.grow(x_r, y_r, m_r, s_r, state_rows=state_rows)
            self.alive = np.concatenate(
                [self.alive, np.ones(events.joins, dtype=bool)]
            )
            self.n_registered += events.joins
            self.totals["joins"] += events.joins
        if events.departs.size:
            self.alive[events.departs] = False
            self.totals["departs"] += int(events.departs.size)
        self.cursor = events.round_idx

    # ---- drift --------------------------------------------------------------
    def _drift_level(self, round_idx: int, rank: int, n_valid: int) -> int:
        """Corrupted-slot count of drift member ``rank`` at ``round_idx``:
        its grade ramping linearly over the run (absolute, not
        incremental — resume-exact by construction)."""
        ramp = min(1.0, (round_idx + 1) / max(self.total_rounds, 1))
        return int(round(self.drift_grades[rank] * ramp * n_valid))

    def apply_drift(self, store, round_idx: int, ids=None) -> None:
        """Set the drifting members of ``ids`` (None = the whole drift
        cohort) to their round-``round_idx`` corruption level, in place
        in the store's label rows. Lazy + absolute: only sampled members
        pay, and re-applying any level is idempotent."""
        if self.drift_ids.size == 0:
            return
        members = (
            self.drift_ids if ids is None
            else np.intersect1d(np.asarray(ids), self.drift_ids)
        )
        for cid in members:
            cid = int(cid)
            rank = self._drift_index[cid]
            pack = self._drift_pack.get(cid)
            if pack is None:
                orig = np.array(store.y[cid], copy=True)
                valid = np.flatnonzero(store.mask[cid] > 0)
                rng = np.random.default_rng(
                    self.seed * 1_000_003 + 7 * cid + 13
                )
                order = valid[rng.permutation(valid.size)]
                if self._num_classes is not None:
                    n_cls = self._num_classes
                else:
                    n_cls = int(store.y.max()) + 1
                noise = rng.integers(
                    0, n_cls, size=order.size
                ).astype(store.y.dtype)
                pack = (orig, order, noise)
                self._drift_pack[cid] = pack
            orig, order, noise = pack
            k = self._drift_level(round_idx, rank, order.size)
            row = np.array(orig, copy=True)
            row[order[:k]] = noise[:k]
            store.y[cid] = row

    # ---- checkpoint / resume ------------------------------------------------
    def checkpoint_state(self, store) -> dict:
        """The registration stream's resume payload: cursor, alive mask,
        and the JOINED clients' shard rows (drawn from past round keys a
        resumed run cannot replay — the initial-N rows re-derive from
        the dataset partition, and drift re-applies lazily from its
        absolute schedule)."""
        return {
            "cursor": self.cursor,
            "n_initial": self.n0,
            "n_registered": int(self.n_registered),
            "alive": self.alive.copy(),
            "joined": {
                "x": np.array(store.x[self.n0:]),
                "y": np.array(store.y[self.n0:]),
                "mask": np.array(store.mask[self.n0:]),
                "sizes": np.array(store.sizes[self.n0:]),
            },
            "totals": dict(self.totals),
        }

    def restore(self, saved: dict, store) -> None:
        """Re-enter a checkpointed population state (resume mid-growth):
        grow the store by the saved joined rows, restore the alive mask
        and cursor. The store must still be at the startup population
        (the caller builds it from the dataset partition first)."""
        if saved["n_initial"] != self.n0:
            raise ValueError(
                f"checkpoint population has n_initial="
                f"{saved['n_initial']}, this run partitions "
                f"{self.n0} startup clients; resume with the "
                "configuration the checkpoint was written with"
            )
        if store.n_clients != self.n0:
            raise ValueError(
                "population restore needs the store at the startup "
                f"population ({self.n0}), got {store.n_clients}"
            )
        j = saved["joined"]
        if j["x"].shape[0]:
            store.grow(j["x"], j["y"], j["mask"], j["sizes"])
        self.n_registered = int(saved["n_registered"])
        if store.n_clients != self.n_registered:
            raise ValueError(
                "checkpoint joined rows do not add up: store has "
                f"{store.n_clients} clients, checkpoint registered "
                f"{self.n_registered}"
            )
        self.alive = np.asarray(saved["alive"], dtype=bool).copy()
        self.totals = dict(saved["totals"])
        self.cursor = int(saved["cursor"])

    # ---- records ------------------------------------------------------------
    def round_record(self, events: PopulationEvents,
                     cohort_departs: int) -> dict:
        """The schema-v9 ``population`` sub-object of this round's
        metrics record (utils/reporting.build_round_record attaches it;
        ``rejected_by_churn`` is filled by the emitter once the round's
        quorum verdict is known)."""
        record = {
            # Startup population on every record: a resumed run's
            # metrics file may not start at round 0, and the reporter's
            # growth ratio must not mistake the resume-time population
            # for the run's origin.
            "n_initial": self.n0,
            "n_registered": int(self.n_registered),
            "n_alive": int(self.alive.sum()),
            "joins": int(events.joins),
            "departs": int(events.departs.size),
            "cohort_departs": int(cohort_departs),
            "drift_cohort_size": int(self.drift_ids.size),
            "rejected_by_churn": False,
        }
        if 0 < self.drift_ids.size <= DRIFT_IDS_CAP:
            # Small planted cohorts list their ids so report_run can
            # overlay them on the valuation tables (the PER_CLIENT_CAP
            # discipline: large cohorts report the size only).
            record["drift_clients"] = [int(c) for c in self.drift_ids]
        return record

    def summary(self, churn_rejected: int = 0) -> dict:
        """The result-dict face of the population (bench.py's churn leg
        reads this)."""
        return {
            "mode": "dynamic",
            "n_initial": self.n0,
            "n_registered": int(self.n_registered),
            "n_alive": int(self.alive.sum()),
            "joins_total": self.totals["joins"],
            "departs_total": self.totals["departs"],
            "growth_ratio": round(self.n_registered / self.n0, 4),
            "drift_cohort_size": int(self.drift_ids.size),
            "rounds_rejected_by_churn": int(churn_rejected),
        }
