"""Failure injection and recovery (docs/ROBUSTNESS.md).

The reference simulator has zero fault tolerance — one worker that never
reports back deadlocks the server's blocking barrier forever
(fed_server.py:75-77). This package provides the *attack* side that the
repo's existing defenses (robust aggregation rules, atomic checkpoints)
were missing: an injectable per-round client failure model
(:mod:`.faults`), a deterministic crash-injection hook for the chaos
harness (:mod:`.chaos`), the asynchronous-federation subsystem —
device-side arrival model, deadline rounds, staleness buffer
(:mod:`.arrivals`) — and the open-world dynamic-population layer:
a round-key-chained registration stream of client joins, departures,
and drifting data quality (:mod:`.population`).
"""

from distributed_learning_simulator_tpu.robustness.arrivals import (  # noqa: F401
    AsyncFederation,
    staleness_discount,
)
from distributed_learning_simulator_tpu.robustness.chaos import (  # noqa: F401
    InjectedCrash,
    maybe_crash,
)
from distributed_learning_simulator_tpu.robustness.faults import (  # noqa: F401
    FailureModel,
    all_finite,
)
from distributed_learning_simulator_tpu.robustness.population import (  # noqa: F401
    PopulationEvents,
    PopulationModel,
)
