"""Deterministic crash injection for the chaos harness.

``run_simulation`` calls :func:`maybe_crash` at the end of every round's
finalize step — AFTER that round's metrics line and checkpoint (if due)
are on disk — so the injected failure models "the process died right
after persisting round N". Three kinds, selected by environment variables
so the same hook drives in-process tests, subprocess SIGKILL tests, and
the SIGTERM grace-path test without any test-only wiring in the
simulator:

  * ``DLS_CRASH_KIND=raise`` (default) — raise :class:`InjectedCrash`;
    the exception unwinds through the host loop's crash-flush paths
    (useful in-process: pytest catches it).
  * ``DLS_CRASH_KIND=sigkill`` — ``SIGKILL`` to self: no cleanup, no
    ``finally`` blocks, no atexit — the torn-state variant a real
    preemption or OOM-kill produces.
  * ``DLS_CRASH_KIND=sigterm`` — ``SIGTERM`` to self: exercises the
    graceful-preemption path (finish the in-flight round, write a final
    checkpoint, exit cleanly) deterministically instead of racing a
    parent-process kill timer.

The hook is inert unless ``DLS_CRASH_AT_ROUND`` is set, and costs one
environment lookup per round.
"""

from __future__ import annotations

import os
import signal

ENV_CRASH_ROUND = "DLS_CRASH_AT_ROUND"
ENV_CRASH_KIND = "DLS_CRASH_KIND"


class InjectedCrash(RuntimeError):
    """Raised by the ``raise`` crash kind; never by production code paths."""


def maybe_crash(round_idx: int) -> None:
    """Kill this process if ``DLS_CRASH_AT_ROUND`` names ``round_idx``."""
    target = os.environ.get(ENV_CRASH_ROUND)
    if target is None:
        return
    try:
        target_round = int(target)
    except ValueError as e:
        raise ValueError(
            f"{ENV_CRASH_ROUND}={target!r} is not an integer round index"
        ) from e
    if target_round != round_idx:
        return
    kind = os.environ.get(ENV_CRASH_KIND, "raise").lower()
    if kind == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
    elif kind == "sigterm":
        os.kill(os.getpid(), signal.SIGTERM)
        return  # handler only sets a flag; the round loop exits gracefully
    else:
        raise InjectedCrash(
            f"injected crash after round {round_idx} was persisted"
        )
