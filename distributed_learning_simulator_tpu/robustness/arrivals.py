"""Asynchronous federation: device-side arrival model, deadline rounds,
and buffered staleness-weighted aggregation — all INSIDE the jitted round.

Every algorithm in this repo was synchronous-round: the straggler fault
(:mod:`robustness.faults`) simulates slow clients, but the server could
only wait or drop them — a straggler's upload was discarded forever, the
opposite of graceful degradation. FedML Parrot (PAPERS.md) makes
heterogeneity-aware scheduling a simulator primitive; FedBuff-style
buffered aggregation (Nguyen et al.) is the standard server answer to
stragglers. This module brings both to the one-XLA-program round design,
with BlazeFL's fast-and-*deterministic* bar: ``async_mode='off'`` (the
default) compiles the exact pre-feature program, and
``round_deadline=inf`` makes the compiled *async* program bit-identical
to synchronous FedAvg (tests/test_async.py).

Design, mirroring :class:`~robustness.faults.FailureModel`:

* :class:`AsyncFederation` is built from config (``async_mode='off'``
  returns None, and every call site gates at TRACE time on that).
* **Arrival model** (``arrival_model={bimodal,lognormal}``): each
  client has a persistent speed factor drawn from its TRUE client index
  under ``arrival_seed`` — an ``arrival_slow_fraction`` share of the
  population is ``arrival_slow_factor``× slower (the 80/20 fast/slow
  knob) — times a per-round jitter drawn from the ROUND key via
  ``fold_in`` (uniform [0.5, 1.5) for ``bimodal``,
  ``exp(sigma · N(0,1))`` for ``lognormal``). The fold_in-decoupled
  stream means activating arrivals re-rolls NOTHING else: cohort
  sampling, failure draws, training batches and payload keys are
  untouched (the same discipline as ``failure_seed``).
* **Deadline rounds**: clients whose latency is at most
  ``round_deadline`` contribute *fresh*, exactly like synchronous
  FedAvg over the on-time sub-cohort. The server closes the round at
  ``min(round_deadline, max latency)`` of simulated time — the advancing
  simulated wall-clock whose sum, against the synchronous counterfactual
  ``max latency`` (wait for everyone), is the run's
  ``async_speedup_ratio``.
* **Staleness buffer**: a late upload's *delta* (vs the global model it
  trained from) lands in a device-resident accumulator with weight
  ``size · (1 + s)^(-staleness_alpha)``, where the staleness ``s`` is
  how many rounds late the upload arrives (``ceil(latency/deadline) -
  1``; a fault-routed straggler is at least 1). The discount is fixed at
  insertion — the buffer holds ONE param-sized tree regardless of how
  many uploads it absorbs, so buffer memory never scales with
  ``async_buffer_size`` or the model. When the buffered-upload count
  reaches ``async_buffer_size`` (FedBuff's K-of-N trigger), the
  buffered mean delta is applied alongside that round's fresh aggregate,
  weighted by its share of the combined weight, and the buffer resets.
  Stale deltas applied to a moved global model are the standard
  async-FL semantics (the staleness the discount pays for).
* A non-finite late batch (a ``corrupt_nan`` client missing the
  deadline) is dropped at insertion (:func:`~robustness.faults.
  all_finite` guard) — one poisoned upload must not brick the buffer
  for the rest of the run. A quorum-rejected round keeps its inserts
  but reverts any trigger/reset (the late arrivals really arrived; the
  poisoned aggregate is what was refused).

Composition matrix, semantics and the acceptance evidence:
docs/ROBUSTNESS.md § Asynchronous federation.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp

from distributed_learning_simulator_tpu.robustness.faults import all_finite

ARRIVAL_MODES = ("none", "bimodal", "lognormal")
ASYNC_MODES = ("off", "on")

#: fold_in tag separating the arrival stream from every other consumer
#: of the round key (no other module folds the raw round key).
_ARRIVAL_STREAM = 0x61727276  # "arrv"


def staleness_discount(staleness, alpha: float):
    """Polynomial staleness discount ``(1 + s)^(-alpha)`` (FedBuff /
    Xie et al. "Asynchronous Federated Optimization"): ``alpha=0`` keeps
    late updates at full weight, larger ``alpha`` trusts them less."""
    return (1.0 + staleness) ** (-alpha)


@dataclass(frozen=True)
class AsyncFederation:
    """Static (trace-time) async-federation configuration; per-round
    draws and the buffer update are pure functions of the round key and
    the carried buffer state, so one compiled round program serves every
    round."""

    arrival_model: str
    slow_fraction: float
    slow_factor: float
    sigma: float
    seed: int
    deadline: float
    buffer_size: int
    alpha: float

    @classmethod
    def from_config(cls, config) -> "AsyncFederation | None":
        """None when ``async_mode='off'`` (the default) — callers gate
        every trace-time branch on that, so synchronous runs compile the
        exact pre-feature program."""
        mode = (getattr(config, "async_mode", "off") or "off").lower()
        if mode == "off":
            return None
        if mode not in ASYNC_MODES:
            raise ValueError(
                f"unknown async_mode {mode!r}; known: "
                + ", ".join(ASYNC_MODES)
            )
        arrival = getattr(config, "arrival_model", "none") or "none"
        if arrival == "none":
            raise ValueError(
                "async_mode='on' needs an arrival model to order uploads "
                "against round_deadline; set arrival_model='bimodal' or "
                "'lognormal'"
            )
        if arrival not in ARRIVAL_MODES:
            raise ValueError(
                f"unknown arrival_model {arrival!r}; known: "
                + ", ".join(ARRIVAL_MODES)
            )
        return cls(
            arrival_model=arrival,
            slow_fraction=float(getattr(config, "arrival_slow_fraction", 0.2)),
            slow_factor=float(getattr(config, "arrival_slow_factor", 8.0)),
            sigma=float(getattr(config, "arrival_sigma", 0.5)),
            seed=int(getattr(config, "arrival_seed", 0)),
            deadline=float(getattr(config, "round_deadline", float("inf"))),
            buffer_size=int(getattr(config, "async_buffer_size", 8)),
            alpha=float(getattr(config, "staleness_alpha", 0.5)),
        )

    # ---- jit-side draws ----------------------------------------------------
    def speed_factors(self, client_ids):
        """Persistent ``[n]`` per-client slowdown factors (1.0 for the
        fast population, ``slow_factor`` for the slow one). Keyed by the
        TRUE client index under ``arrival_seed`` only — a client keeps
        its speed across rounds, participation sampling, and resume."""
        k = jax.random.fold_in(jax.random.key(self.seed), _ARRIVAL_STREAM)
        u = jax.vmap(
            lambda i: jax.random.uniform(jax.random.fold_in(k, i))
        )(client_ids)
        return jnp.where(
            u < self.slow_fraction,
            jnp.float32(self.slow_factor),
            jnp.float32(1.0),
        )

    def speed_table(self, n_clients: int):
        """The whole population's :meth:`speed_factors` as one ``[n]``
        table. Built EAGERLY once at round-fn construction and closed
        over as a constant: the factors depend only on ``arrival_seed``
        and the client index, so recomputing the per-client fold_in
        chains inside the compiled round (×K under round batching)
        would be pure waste — the round program just gathers from the
        table."""
        return self.speed_factors(jnp.arange(n_clients))

    def draw_latency(self, key, client_ids, speeds=None):
        """``[n]`` simulated upload latencies for one round's cohort
        (speed factor × per-round jitter, in ``round_deadline`` units).
        ``speeds`` — the cohort's rows of :meth:`speed_table`; derived
        from ``client_ids`` when omitted (same values either way).

        ``fold_in(key, tag/seed)`` decouples the arrival stream from
        every other consumer of the round key: the splits the
        synchronous program draws are untouched, which is what makes the
        ``round_deadline=inf`` degenerate case bit-identical to sync —
        sampling, failure draws, and batch shuffles included.
        """
        k = jax.random.fold_in(
            jax.random.fold_in(key, _ARRIVAL_STREAM), self.seed
        )
        n = client_ids.shape[0]
        if self.arrival_model == "bimodal":
            jitter = jax.random.uniform(k, (n,), minval=0.5, maxval=1.5)
        else:  # lognormal (from_config validated the name set)
            jitter = jnp.exp(self.sigma * jax.random.normal(k, (n,)))
        if speeds is None:
            speeds = self.speed_factors(client_ids)
        return speeds * jitter

    def classify(self, latency, forced_late=None):
        """Split one round's cohort against the deadline.

        Returns ``(on_time, staleness, discount, eff_latency)``: a bool
        ``[n]`` mask, the integer-valued f32 staleness (rounds late:
        ``ceil(latency/deadline) - 1``, at least 1 for ``forced_late``
        clients — the straggler fault routed into the buffer), the
        per-client :func:`staleness_discount`, and the EFFECTIVE
        latencies: a fault-routed straggler's upload is delayed one full
        deadline past its drawn arrival, so the simulated clock
        (:meth:`durations`) pays for the very stragglers the routing
        buffers — staleness and clock stay consistent. At
        ``deadline=inf`` there is no deadline to miss: non-forced
        clients are on time at staleness 0, forced clients keep their
        drawn latency (finite telemetry) with staleness floored at 1.
        """
        if forced_late is not None and math.isfinite(self.deadline):
            latency = jnp.where(
                forced_late, latency + jnp.float32(self.deadline), latency
            )
        on_time = latency <= self.deadline
        s = jnp.maximum(jnp.ceil(latency / self.deadline) - 1.0, 0.0)
        if forced_late is not None:
            on_time = on_time & ~forced_late
            s = jnp.where(forced_late, jnp.maximum(s, 1.0), s)
        return on_time, s, staleness_discount(s, self.alpha), latency

    def durations(self, latency):
        """Simulated round durations ``(async, sync)``: the deadline
        server closes at ``min(deadline, max latency)``; the synchronous
        counterfactual waits for the whole cohort (``max latency`` — the
        reference's blocking barrier, idealized to terminate)."""
        slowest = jnp.max(latency)
        return jnp.minimum(slowest, jnp.float32(self.deadline)), slowest

    # ---- buffer carry ------------------------------------------------------
    def init_state(self, global_params) -> dict:
        """Round-0 buffer state: one f32 param-sized accumulator of
        discounted late deltas plus three scalars. This dict is the
        round program's async carry — threaded through
        ``rounds_per_dispatch`` scans, checkpointed, and restored on
        resume like every other piece of round state."""
        return {
            "buf_sum": jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), global_params
            ),
            "buf_weight": jnp.zeros((), jnp.float32),
            "buf_count": jnp.zeros((), jnp.int32),
            "clock": jnp.zeros((), jnp.float32),
        }

    def absorb_and_apply(self, state, global_params, fresh_agg, a_tot,
                         late_sum, b_tot, n_late, sim_duration):
        """One round's buffer step: insert the late batch, fire the
        K-of-N trigger, produce the round's aggregate.

        Inputs: ``fresh_agg`` — the on-time cohort's aggregate, computed
        with the synchronous formula over on-time weights summing to
        ``a_tot``; ``late_sum`` — the discounted weighted SUM of late
        clients' (payload-processed) params with total weight ``b_tot``
        over ``n_late`` uploads. ``late_sum - b_tot·g`` is the late
        batch's delta vs this round's global — stale by construction
        when applied later.

        Returns ``(new_global, applied, state_inserted, state_next)``:
        ``new_global`` is ``fresh_agg`` untouched (bit-exact
        select) unless the trigger fired, in which case the buffered
        mean delta joins at its ``buf_weight/(a_tot + buf_weight)``
        share; ``state_inserted`` keeps the inserts without the reset
        (what a quorum-REJECTED round must carry forward — the late
        arrivals really arrived); ``state_next`` is the normal
        post-round state (reset when applied). A non-finite late batch
        is dropped whole at insertion so the buffer stays finite.
        """
        g32 = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), global_params
        )
        late_delta = jax.tree_util.tree_map(
            lambda ls, g: ls - b_tot * g, late_sum, g32
        )
        # Coarse by design: one NaN late upload drops the whole round's
        # late batch (per-upload finiteness would need per-client
        # reductions the fused path avoids); the honest late clients
        # lose one insert, the buffer survives the run.
        ins_ok = all_finite(late_delta) & (n_late > 0)
        buf_sum = jax.tree_util.tree_map(
            lambda b, d: b + jnp.where(ins_ok, d, 0.0),
            state["buf_sum"], late_delta,
        )
        buf_weight = state["buf_weight"] + jnp.where(ins_ok, b_tot, 0.0)
        buf_count = state["buf_count"] + jnp.where(
            ins_ok, n_late, jnp.int32(0)
        )
        applied = buf_count >= self.buffer_size
        a_f = a_tot.astype(jnp.float32)
        beta = jnp.where(
            applied, buf_weight / jnp.maximum(a_f + buf_weight, 1e-12), 0.0
        )
        a_pos = a_f > 0
        combined = jax.tree_util.tree_map(
            # Fresh delta zeroed (not multiplied) when the on-time cohort
            # is empty: 0 * NaN would poison a buffer-only round.
            lambda g, f, b: (
                g
                + (1.0 - beta)
                * jnp.where(a_pos, f.astype(jnp.float32) - g, 0.0)
                + beta * (b / jnp.maximum(buf_weight, 1e-12))
            ),
            g32, fresh_agg, buf_sum,
        )
        new_global = jax.tree_util.tree_map(
            lambda f, c: jnp.where(applied, c.astype(f.dtype), f),
            fresh_agg, combined,
        )
        clock = state["clock"] + sim_duration
        state_inserted = {
            "buf_sum": buf_sum,
            "buf_weight": buf_weight,
            "buf_count": buf_count,
            "clock": clock,
        }
        state_next = {
            "buf_sum": jax.tree_util.tree_map(
                lambda b: jnp.where(applied, jnp.zeros_like(b), b), buf_sum
            ),
            "buf_weight": jnp.where(applied, 0.0, buf_weight),
            "buf_count": jnp.where(applied, jnp.int32(0), buf_count),
            "clock": clock,
        }
        return new_global, applied, state_inserted, state_next
