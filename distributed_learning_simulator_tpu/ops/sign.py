"""SignSGD compression + majority vote.

Replaces the reference's SignSGD server/worker pair
(servers/sign_sgd_server.py:13-21 and workers/sign_sgd_worker.py:44-46):
each client signs its effective update direction (1-bit compression), the
server sums the signs elementwise and re-signs (majority vote), and the voted
sign is broadcast back. Here the whole vote is one reduction over the client
axis, fused by XLA into the training step (see algorithms/sign_sgd.py). Note
the reference's server is mis-wired (its vote method is never called,
SURVEY 2.1#13) — this is the intended, fixed semantics.

Sign convention matches ``torch.sign``: sign(0) = 0, and a tied vote
broadcasts 0 (no update for that element).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sign_compress(tree):
    """Elementwise sign of every leaf: the 1-bit client payload."""
    return jax.tree_util.tree_map(jnp.sign, tree)


def majority_vote(stacked_sign_tree):
    """Elementwise ``sign(sum(signs))`` over the leading (client) axis.

    Parity with reference sign_sgd_server.py:16-18. On a sharded client axis
    the inner sum lowers to an ICI psum.
    """
    return jax.tree_util.tree_map(
        lambda x: jnp.sign(jnp.sum(x, axis=0)), stacked_sign_tree
    )


# ---- torch-SGD step math, single source -----------------------------------
# The vmap round program (algorithms/sign_sgd.py) and the thread-per-client
# mode (execution/threaded.py) are a differential-testing oracle pair: both
# must implement EXACTLY the reference worker's update math
# (sign_sgd_worker.py:22-42 momentum, :47-58 apply). These leaf-level
# formulas are the one copy both consume.

def momentum_leaf(m, g, is_first, mu, dampening):
    """torch-SGD momentum buffer update for one leaf: the very first step
    initializes buf to the raw gradient (torch's buf-is-None branch), later
    steps apply ``mu*buf + (1-dampening)*grad``. ``is_first`` must be
    broadcastable against the leaf."""
    return jnp.where(is_first, g, mu * m + (1.0 - dampening) * g)


def direction_leaf(g, m_new, mu, nesterov):
    """Effective update direction for one leaf after the momentum update:
    ``g + mu*buf`` under nesterov, else the buffer itself."""
    return g + mu * m_new if nesterov else m_new


def vote_apply_leaf(p, voted, lr, wd):
    """Apply the voted sign locally: weight decay + ``p - lr*sign``
    (sign_sgd_worker.py:47-58)."""
    return p - lr * (voted + wd * p)
