"""SignSGD compression + majority vote.

Replaces the reference's SignSGD server/worker pair
(servers/sign_sgd_server.py:13-21 and workers/sign_sgd_worker.py:44-46):
each client signs its effective update direction (1-bit compression), the
server sums the signs elementwise and re-signs (majority vote), and the voted
sign is broadcast back. Here the whole vote is one reduction over the client
axis, fused by XLA into the training step (see algorithms/sign_sgd.py). Note
the reference's server is mis-wired (its vote method is never called,
SURVEY 2.1#13) — this is the intended, fixed semantics.

Sign convention matches ``torch.sign``: sign(0) = 0, and a tied vote
broadcasts 0 (no update for that element).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sign_compress(tree):
    """Elementwise sign of every leaf: the 1-bit client payload."""
    return jax.tree_util.tree_map(jnp.sign, tree)


def majority_vote(stacked_sign_tree):
    """Elementwise ``sign(sum(signs))`` over the leading (client) axis.

    Parity with reference sign_sgd_server.py:16-18. On a sharded client axis
    the inner sum lowers to an ICI psum.
    """
    return jax.tree_util.tree_map(
        lambda x: jnp.sign(jnp.sum(x, axis=0)), stacked_sign_tree
    )
