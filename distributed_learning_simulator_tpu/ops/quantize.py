"""Quantization ops: stochastic payload quantization + fake-quant (QAT).

TPU-native replacement for the reference's quantization stack:

  * ``stochastic_quantization(level)`` from the external lib (reference
    servers/fed_quant_server.py:2-3,37-39) -> :func:`stochastic_quantize` /
    :func:`dequantize`: affine quantization to ``levels`` levels with
    *stochastic rounding*, unbiased in expectation.
  * PyTorch's ``QuantizationAwareTraining`` + ``QuantStub`` machinery
    (reference workers/fed_quant_worker.py:19-20, quant_model.py:4-19) has no
    JAX twin; QAT here is :func:`fake_quant` — a straight-through-estimator
    round-trip applied to parameters inside the loss, which is the idiomatic
    XLA formulation (elementwise ops fused into the training step).

Everything is elementwise and jit/vmap-safe; the quantized representation is
``(codes, scale, zero_point)`` with ``dequant = (codes - zero_point) * scale``,
matching the reference's dequantization formula (fed_quant_server.py:25-33).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    """Affine-quantized tensor: ``value ~= (codes - zero_point) * scale``."""

    codes: jax.Array  # float32 integer-valued codes in [0, levels-1]
    scale: jax.Array  # scalar
    zero_point: jax.Array  # scalar, in the quantized domain


def _minmax(x):
    """(min, max) of ``x`` in ONE pass over the data, in x's own dtype.

    Separate ``jnp.min``/``jnp.max`` calls lower to two XLA reduces — two
    full reads of the tensor — and the QAT path runs them on every param
    leaf of every client at every optimizer step (measured 563 ms/round =
    21% of the flagship fed_quant round, 332 GB of pure range-pass
    traffic). A variadic ``lax.reduce`` computes both extrema in one read.
    Reducing in the input dtype is exact (min/max select, they never
    round), so the resulting affine params are bit-identical to the old
    upcast-then-reduce formulation.
    """
    if x.size == 0:
        # jnp.min/jnp.max raised loudly here; the init-value reduce would
        # silently return (inf, -inf) and poison scale/zero_point.
        raise ValueError("cannot quantize a zero-size tensor")
    return jax.lax.reduce(
        (x, x),
        (jnp.asarray(jnp.inf, x.dtype), jnp.asarray(-jnp.inf, x.dtype)),
        lambda a, b: (jnp.minimum(a[0], b[0]), jnp.maximum(a[1], b[1])),
        tuple(range(x.ndim)),
    )


def _affine_params(x, levels: int):
    if x.dtype not in (jnp.float32, jnp.bfloat16, jnp.float16, jnp.float64):
        # The in-dtype range pass needs +-inf init values: ints/bools have
        # none (OverflowError) and fp8 e4m3fn converts inf to NaN. Those
        # inputs gain nothing from the in-dtype read anyway — upcast.
        x = x.astype(jnp.float32)
    xmin, xmax = _minmax(x)
    xmin = xmin.astype(jnp.float32)
    xmax = xmax.astype(jnp.float32)
    span = xmax - xmin
    scale = jnp.where(span > 0, span / (levels - 1), 1.0)
    zero_point = -xmin / scale
    return scale, zero_point


def hash_mix(u, salt):
    """Two-round multiplicative hash of uint32 ``u`` mixed with ``salt``.

    THE one copy of the dither-hash mixing (statistical quality is
    certified by the SR/quantize unbiasedness tests): shared by the
    engine's bf16 stochastic rounding (parallel/engine.py ``_sr_to_bf16``)
    and the quantized-payload stochastic rounding below. Pure fused
    elementwise ALU — no PRNG tensor is generated or moved.
    """
    h = u * jnp.uint32(2654435761) ^ (u >> 13) ^ salt
    return h * jnp.uint32(2246822519) ^ (h >> 16)


def _salt_from_key(key) -> jax.Array:
    """Fold a JAX PRNG key (typed or raw uint32 data) into a uint32 salt."""
    if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
        kd = jax.random.key_data(key)
    else:
        kd = key
    kd = kd.reshape(-1).astype(jnp.uint32)
    return kd[0] * jnp.uint32(0x9E3779B9) ^ kd[-1]


def _dither_u01(x32, salt) -> jax.Array:
    """Uniform [0, 1) dither from a multiplicative hash of the value bits
    mixed with ``salt`` — the same pure-ALU mechanism as the engine's
    bf16 stochastic rounding (parallel/engine.py ``_sr_to_bf16``).

    Exists because a real counter PRNG is a measured round cost here: with
    ``jax.random.bernoulli`` the threefry bit generation fused into the
    uplink's aggregation partials and dragged them from ~900 GB/s to
    78-92 GB/s (~0.4 s/round on the flagship fed_quant config — the entire
    gap to plain fed). The hash is free: no random tensor is generated or
    moved, and decorrelation across clients comes from the per-client
    salt (the same load-bearing property as hash-dither SR —
    docs/PERFORMANCE.md round 2).
    """
    u = jax.lax.bitcast_convert_type(x32, jnp.uint32)
    h = hash_mix(u, salt)
    return (h >> 8).astype(jnp.float32) * jnp.float32(2.0**-24)


def stochastic_quantize(x, levels: int, key) -> QuantizedTensor:
    """Quantize ``x`` to ``levels`` levels with stochastic rounding.

    Unbiased: ``E[dequantize(stochastic_quantize(x))] = x`` (the round-up
    indicator is ``floor(n + u) - floor(n)`` with ``u`` uniform [0, 1), so
    ``P[up] = frac(n)``; tests/test_quantize.py averages over keys).
    Parity with the external ``stochastic_quantization`` used at
    fed_quant_server.py:37-39 (256 levels = 8-bit). The randomness is a
    hash dither keyed by ``key`` (see :func:`_dither_u01` for why a
    counter PRNG is disqualified here).
    """
    x = jnp.asarray(x)  # array-likes in; range pass stays in x's own dtype
    scale, zero_point = _affine_params(x, levels)
    x = jnp.asarray(x, dtype=jnp.float32)
    normalized = x / scale + zero_point
    dither = _dither_u01(normalized, _salt_from_key(key))
    codes = jnp.clip(jnp.floor(normalized + dither), 0, levels - 1)
    return QuantizedTensor(codes=codes, scale=scale, zero_point=zero_point)


def dequantize(q: QuantizedTensor) -> jax.Array:
    """Inverse affine map (reference fed_quant_server.py:31-33)."""
    return (q.codes - q.zero_point) * q.scale


def stochastic_quantize_tree(tree, levels: int, key):
    """Per-leaf stochastic quantization of a whole params pytree."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))
    q_leaves = [stochastic_quantize(x, levels, k) for x, k in zip(leaves, keys)]
    return jax.tree_util.tree_unflatten(treedef, q_leaves)


def dequantize_tree(q_tree):
    """Per-leaf dequantization; inverse of :func:`stochastic_quantize_tree`."""
    return jax.tree_util.tree_map(
        dequantize, q_tree, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


def fake_quant(x, levels: int):
    """Deterministic quantize->dequantize with a straight-through gradient.

    Forward: nearest-level affine round-trip. Backward: identity (STE).
    This is the QAT primitive replacing PyTorch's fake-quant observers
    (reference quant_model.py:9-11); applying it to params inside the loss
    trains a model robust to ``levels``-level parameter quantization.

    The round-trip arithmetic runs in f32 (bf16 integer codes near
    ``levels-1`` have a 2-ulp spacing and would mis-round), but the result
    is cast back to ``x.dtype``: the transformed params feed bf16 MXU
    convs anyway, and keeping the output in the storage dtype lets the
    whole transform fuse into the step instead of materializing an f32
    copy of every client's parameter tree.
    """
    x = jnp.asarray(x)  # array-likes in; range pass stays in x's own dtype
    in_dtype = x.dtype
    # Range pass BEFORE the f32 upcast: the reduce reads the tensor in its
    # storage dtype (half the bytes under bf16 state) and the upcast stays
    # a fusible elementwise step instead of a materialized copy feeding
    # two reduces. bf16 -> f32 is exact, so the affine params match the
    # upcast-then-reduce formulation bitwise.
    scale, zero_point = _affine_params(jax.lax.stop_gradient(x), levels)
    x = jnp.asarray(x, dtype=jnp.float32)
    codes = jnp.clip(jnp.round(x / scale + zero_point), 0, levels - 1)
    dq = (codes - zero_point) * scale
    return (x + jax.lax.stop_gradient(dq - x)).astype(in_dtype)


def fake_quant_tree(tree, levels: int):
    """Apply :func:`fake_quant` to every leaf of a params pytree."""
    return jax.tree_util.tree_map(lambda x: fake_quant(x, levels), tree)
