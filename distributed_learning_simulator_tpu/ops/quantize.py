"""Quantization ops: stochastic payload quantization + fake-quant (QAT).

TPU-native replacement for the reference's quantization stack:

  * ``stochastic_quantization(level)`` from the external lib (reference
    servers/fed_quant_server.py:2-3,37-39) -> :func:`stochastic_quantize` /
    :func:`dequantize`: affine quantization to ``levels`` levels with
    *stochastic rounding*, unbiased in expectation.
  * PyTorch's ``QuantizationAwareTraining`` + ``QuantStub`` machinery
    (reference workers/fed_quant_worker.py:19-20, quant_model.py:4-19) has no
    JAX twin; QAT here is :func:`fake_quant` — a straight-through-estimator
    round-trip applied to parameters inside the loss, which is the idiomatic
    XLA formulation (elementwise ops fused into the training step).

Everything is elementwise and jit/vmap-safe; the quantized representation is
``(codes, scale, zero_point)`` with ``dequant = (codes - zero_point) * scale``,
matching the reference's dequantization formula (fed_quant_server.py:25-33).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class QuantizedTensor(NamedTuple):
    """Affine-quantized tensor: ``value ~= (codes - zero_point) * scale``."""

    codes: jax.Array  # float32 integer-valued codes in [0, levels-1]
    scale: jax.Array  # scalar
    zero_point: jax.Array  # scalar, in the quantized domain


def _affine_params(x, levels: int):
    xmin = jnp.min(x)
    xmax = jnp.max(x)
    span = xmax - xmin
    scale = jnp.where(span > 0, span / (levels - 1), 1.0)
    zero_point = -xmin / scale
    return scale, zero_point


def stochastic_quantize(x, levels: int, key) -> QuantizedTensor:
    """Quantize ``x`` to ``levels`` levels with stochastic rounding.

    Unbiased: ``E[dequantize(stochastic_quantize(x))] = x``. Parity with the
    external ``stochastic_quantization`` used at fed_quant_server.py:37-39
    (256 levels = 8-bit).
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    scale, zero_point = _affine_params(x, levels)
    normalized = x / scale + zero_point
    floor = jnp.floor(normalized)
    frac = normalized - floor
    up = jax.random.bernoulli(key, frac.astype(jnp.float32))
    codes = jnp.clip(floor + up.astype(jnp.float32), 0, levels - 1)
    return QuantizedTensor(codes=codes, scale=scale, zero_point=zero_point)


def dequantize(q: QuantizedTensor) -> jax.Array:
    """Inverse affine map (reference fed_quant_server.py:31-33)."""
    return (q.codes - q.zero_point) * q.scale


def stochastic_quantize_tree(tree, levels: int, key):
    """Per-leaf stochastic quantization of a whole params pytree."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(key, len(leaves))
    q_leaves = [stochastic_quantize(x, levels, k) for x, k in zip(leaves, keys)]
    return jax.tree_util.tree_unflatten(treedef, q_leaves)


def dequantize_tree(q_tree):
    """Per-leaf dequantization; inverse of :func:`stochastic_quantize_tree`."""
    return jax.tree_util.tree_map(
        dequantize, q_tree, is_leaf=lambda x: isinstance(x, QuantizedTensor)
    )


def fake_quant(x, levels: int):
    """Deterministic quantize->dequantize with a straight-through gradient.

    Forward: nearest-level affine round-trip. Backward: identity (STE).
    This is the QAT primitive replacing PyTorch's fake-quant observers
    (reference quant_model.py:9-11); applying it to params inside the loss
    trains a model robust to ``levels``-level parameter quantization.
    """
    x = jnp.asarray(x, dtype=jnp.float32)
    scale, zero_point = _affine_params(jax.lax.stop_gradient(x), levels)
    codes = jnp.clip(jnp.round(x / scale + zero_point), 0, levels - 1)
    dq = (codes - zero_point) * scale
    return x + jax.lax.stop_gradient(dq - x)


def fake_quant_tree(tree, levels: int):
    """Apply :func:`fake_quant` to every leaf of a params pytree."""
    return jax.tree_util.tree_map(lambda x: fake_quant(x, levels), tree)
