"""Analytic communication-payload accounting.

The reference measures compression ratios by pickling tensors and comparing
byte counts (servers/fed_quant_server.py:41-48, workers/fed_quant_worker.py:
42-50). On TPU nothing is serialized — clients and server live in one XLA
program — so payload size is defined *analytically*: bits-per-element x numel
plus per-tensor metadata. This keeps the reference's compression-ratio logs
(semantics parity) without host round-trips.
"""

from __future__ import annotations

import jax

from distributed_learning_simulator_tpu.utils.tree import tree_bytes


def payload_bytes(tree) -> int:
    """Uncompressed payload size: every leaf at its native dtype width."""
    return tree_bytes(tree)


def quantized_payload_bytes(tree, levels: int) -> int:
    """Size of the same pytree quantized to ``levels`` levels.

    ceil(log2(levels)) bits per element, plus 8 bytes (scale + zero_point as
    float32) per tensor of metadata.
    """
    bits = max(1, (levels - 1).bit_length())
    n_tensors = len(jax.tree_util.tree_leaves(tree))
    return tree_bytes(tree, bits_per_element=bits) + 8 * n_tensors


def sign_payload_bytes(tree) -> int:
    """1-bit-per-element sign payload (SignSGD uploads)."""
    return tree_bytes(tree, bits_per_element=1)


def compression_ratio(original_bytes: int, compressed_bytes: int) -> float:
    """original/compressed, parity with the reference's ratio logs."""
    return original_bytes / max(1, compressed_bytes)
