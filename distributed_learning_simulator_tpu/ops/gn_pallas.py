"""Pallas GroupNorm forward: bf16-in, one-pass stats, no f32 activations.

MEASURED NEGATIVE RESULT — kept as an opt-in (``DLS_GN_PALLAS=1``) with
the evidence recorded; the default path is the jnp forward in
models/resnet.py. In-context rounds got SLOWER with these kernels
(sign_SGD 2.72 -> 3.37 s/round, fed flagship 2.22 -> 2.84) even though
they deliver exactly the byte-level property the trace analysis asked
for — see the story below and `_use_pallas_gn` in models/resnet.py.

Why it was built (round 5, HLO + device-trace evidence): with the jnp
GroupNorm forward, XLA fuses the stats' ``astype(f32)`` into the
PRODUCING conv's epilogue (``convolution_convert_fusion``), so the conv
writes the stage activations in f32 and every consumer — the stats
reduce, the normalize pass, and the next conv's weight-grad recompute —
re-reads them at 2x bytes; on the flagship ResNet round this f32 tax
plus the associated relayout copies is ~0.4 s/round. Neither
re-orienting the layout (HWNC) nor ``optimization_barrier`` removed it
in context (both measured slower overall — models/resnet.py module
docstring). A Pallas kernel is an *opaque* consumer: the conv must emit
bf16, the stats kernel converts in-register and reads the activations
exactly once, and the normalize kernel reads them once more with small
per-(sample, channel) f32 coefficient rows. That all happens — and the
fusion XLA loses at the opaque boundary (normalize/relu/residual/wgrad
recompute stitched into neighboring ops) costs more than the bytes
saved. The f32 epilogue is XLA's side of a trade it is winning.

Semantics match the jnp forms in models/resnet.py to fp-reduction
tolerance: one-pass E[x^2]-E[x]^2 statistics, subtract-first normalize
``y = (x - mean) * (rstd * scale) + bias``. The closed-form BACKWARD
stays jnp (models/resnet.py `_fgn_bwd`/`_pgn_bwd`): its reduces already
read the bf16 residuals inline (trace-verified), so there is nothing to
win there.

Shapes: callers flatten to ``x [B, HW, C]``; group structure is carried
by a channel->group index (folded layouts pool the two tx channel blocks
into the same group — models/resnet.py `FoldedGroupNorm`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl


def _batch_tile(b: int) -> int:
    """Mosaic block rule: the [bt, C] stats blocks need bt % 8 == 0 or
    bt == b. b=8k (big eval batches, flattened client stacks) tiles at 8;
    otherwise one whole-array block (b=25 per-client batches: the
    [25, 512, 128] bf16 input block is 3.3 MB — well inside VMEM)."""
    return 8 if b % 8 == 0 else b


def _hw_tile(hw: int) -> int:
    """Row tile: bounds the kernel's in-VMEM f32 intermediates (a whole
    [25, 512, 128] block OOMed the 16 MB scoped vmem under vmap)."""
    return 128 if hw % 128 == 0 else hw


def _stats_kernel(x_ref, s1_ref, s2_ref):
    x = x_ref[...].astype(jnp.float32)  # [bt, ht, C]

    @pl.when(pl.program_id(1) == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    s1_ref[...] += jnp.sum(x, axis=1)
    s2_ref[...] += jnp.sum(x * x, axis=1)


def _norm_kernel(x_ref, m_ref, a_ref, b_ref, y_ref):
    x = x_ref[...].astype(jnp.float32)      # [bt, ht, C]
    m = m_ref[...][:, None, :]              # [bt, 1, C]
    a = a_ref[...][:, None, :]
    bb = b_ref[...][None, :, :]             # [1, 1, C] bias row
    y_ref[...] = ((x - m) * a + bb).astype(y_ref.dtype)


@functools.partial(jax.jit, static_argnums=(1, 2))
def _column_stats(xr, bt: int, ht: int):
    b, hw, c = xr.shape
    return pl.pallas_call(
        _stats_kernel,
        grid=(b // bt, hw // ht),
        in_specs=[pl.BlockSpec((bt, ht, c), lambda i, j: (i, j, 0))],
        out_specs=[
            pl.BlockSpec((bt, c), lambda i, j: (i, 0)),
            pl.BlockSpec((bt, c), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, c), jnp.float32),
            jax.ShapeDtypeStruct((b, c), jnp.float32),
        ],
    )(xr)


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _normalize(xr, mean_c, a_c, bias_c, bt: int, ht: int, out_dtype):
    b, hw, c = xr.shape
    return pl.pallas_call(
        _norm_kernel,
        grid=(b // bt, hw // ht),
        in_specs=[
            pl.BlockSpec((bt, ht, c), lambda i, j: (i, j, 0)),
            pl.BlockSpec((bt, c), lambda i, j: (i, 0)),
            pl.BlockSpec((bt, c), lambda i, j: (i, 0)),
            pl.BlockSpec((1, c), lambda i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((bt, ht, c), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct(xr.shape, out_dtype),
    )(xr, mean_c, a_c, bias_c)


def _per_group(col_stats, g: int, folds: int):
    """[B, C] per-channel sums -> [B, G] per-group sums, exactly.

    folds=1: plain ``[g, cpg]`` channel blocks; folds=2: channel
    ``c' = tx*(c/2) + grp*cpg + i`` (FoldedGroupNorm's layout) — both tx
    blocks of a group pool into the same statistics. Pure f32 VPU adds
    via reshape (a one-hot matmul here runs at the MXU's default
    reduced-precision f32 passes and cost ~2e-3 relative on the means —
    measured)."""
    b, c = col_stats.shape
    base = c // folds
    cpg = base // g
    return jnp.sum(col_stats.reshape(b, folds, g, cpg), axis=(1, 3))


def _per_channel(group_vals, cpg: int, folds: int):
    """[B, G] per-group values -> [B, C] per-channel rows (layout
    inverse of :func:`_per_group`)."""
    return jnp.tile(jnp.repeat(group_vals, cpg, axis=1), (1, folds))


def pallas_group_norm(x, scale_full, bias_full, g: int, eps: float,
                      out_dtype, folds: int):
    """GroupNorm forward on ``x [B, H, W, C]``.

    ``scale_full``/``bias_full`` are per-CHANNEL (length C — already
    tx-tiled by the caller for folded layouts). Returns
    ``(y [B,H,W,C], mean_g [B,G] f32, rstd_g [B,G] f32)``; the caller
    reshapes mean/rstd to its residual convention.
    """
    b, h, w, c = x.shape
    hw = h * w
    xr = x.reshape(b, hw, c)
    bt = _batch_tile(b)
    ht = _hw_tile(hw)
    s1, s2 = _column_stats(xr, bt, ht)
    cnt = hw * (c // g)
    cpg = c // folds // g
    mean_g = _per_group(s1, g, folds) / cnt
    var = jnp.maximum(
        _per_group(s2, g, folds) / cnt - jnp.square(mean_g), 0.0
    )
    rstd_g = jax.lax.rsqrt(var + eps)
    mean_c = _per_channel(mean_g, cpg, folds)          # [B, C]
    a_c = _per_channel(rstd_g, cpg, folds) * scale_full[None, :]
    y = _normalize(
        xr, mean_c, a_c, bias_full[None, :].astype(jnp.float32), bt, ht,
        jnp.dtype(out_dtype),
    )
    return y.reshape(b, h, w, c), mean_g, rstd_g
