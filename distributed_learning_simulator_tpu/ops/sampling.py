"""Participation sampling: the ONE copy of the cohort draw.

``config.participation_sampler`` selects how the round's cohort (the
``cohort_size()`` participants of a ``participation_fraction < 1``
round) is drawn from the round key's ``part_key``:

* ``exact`` (default) — the bit-identical pre-feature draw:
  ``jax.random.choice(part_key, N, (k,), replace=False)``. Uniform over
  ordered k-subsets, but a full O(N log N) permutation per draw — ~1 s
  at N=1e6 on a CPU host, which is what left the streamed-residency
  stream leg host-bound (docs/PERFORMANCE.md § Streamed client state).
* ``hashed`` — an O(cohort) counter-based draw: a Threefry-2x32 keyed
  hash over a draw counter yields a deterministic stream of EXACTLY
  uniform client indices (values past the largest uint32 multiple of N
  are rejected before the modulo — see :func:`_mod_limit` — so there
  is no modulo bias), and the cohort is the FIRST k DISTINCT values of
  that stream (duplicates rejected inside a fixed small over-draw
  block — no full-N permutation, no full-N memory anywhere). Deliberately NOT
  bit-identical to ``exact`` (it is a new sampling mode, gated and
  documented like ``client_residency`` itself), but uniform
  (chi-square-tested, tests/test_sampling.py), duplicate-free, and
  deterministic from the round-key chain.

Both modes are implemented ONCE here and consumed by every cohort-index
producer — the in-program draw in ``algorithms/fedavg.round_fn``
(:func:`draw_cohort`), the streamed-residency host replay
``Algorithm.cohort_indices`` (:func:`draw_cohort_host`), and through
those two, the PR 2/6 fault/arrival key discipline and the valuation
auditor's ``participants`` consumption — so the producers can never
drift again (they used to be two hand-copied ``jax.random.choice``
calls).

The hashed draw's defining property: the selected cohort is a pure
function of (key bits, N, k) — the "first k distinct of the counter
stream" semantics make it independent of the over-draw block size, so
the jitted fixed-shape loop and the numpy mirror
(:func:`hashed_cohort_np`, used on the host replay path where eager
jax dispatch of a while_loop would dominate the O(cohort) work) agree
element-for-element by construction. The Threefry math is written once
over the array-module argument ``xp`` (numpy and jax.numpy share the
API) so the two backends cannot diverge.

Cost note: expected draws to find k distinct of N is
``N * ln(N / (N - k))`` — ~k for k << N (the regime the sampler exists
for), degrading smoothly toward coupon-collector O(N log N) draws as
``participation_fraction`` approaches 1, where ``exact`` is the better
tool anyway (mode-choice guidance: docs/PERFORMANCE.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

#: Valid participation_sampler values. Defined in config.py (the
#: import-light home of valid-value tuples, like TELEMETRY_LEVELS) and
#: re-exported here next to the implementations.
from distributed_learning_simulator_tpu.config import (  # noqa: E402
    PARTICIPATION_SAMPLERS as SAMPLERS,
)

# Threefry-2x32 constants (Salmon et al., SC'11): 4-round rotation
# schedules and the key-schedule parity word.
_ROTS_A = (13, 15, 26, 6)
_ROTS_B = (17, 29, 16, 24)
_PARITY = 0x1BD11BDA


def threefry2x32(xp, k0, k1, x0, x1):
    """Threefry-2x32, 20 rounds, written over the array module ``xp``.

    ``k0``/``k1`` are uint32 key words, ``x0``/``x1`` uint32 counter
    arrays (or scalars). Returns the two output words. One
    implementation serves both backends: ``xp=jnp`` traces into the
    round program, ``xp=np`` runs the host mirror — uint32 arithmetic
    wraps identically in both, which is what the jit==numpy equality
    contract (tests/test_sampling.py) rests on.
    """
    ks0 = xp.asarray(k0, xp.uint32)
    ks1 = xp.asarray(k1, xp.uint32)
    ks2 = ks0 ^ ks1 ^ xp.uint32(_PARITY)
    ks = (ks0, ks1, ks2)
    x0 = xp.asarray(x0, xp.uint32) + ks0
    x1 = xp.asarray(x1, xp.uint32) + ks1
    for i in range(5):
        for r in _ROTS_A if i % 2 == 0 else _ROTS_B:
            x0 = x0 + x1
            x1 = (x1 << xp.uint32(r)) | (x1 >> xp.uint32(32 - r))
            x1 = x1 ^ x0
        x0 = x0 + ks[(i + 1) % 3]
        x1 = x1 + ks[(i + 2) % 3] + xp.uint32(i + 1)
    return x0, x1


def _key_words(part_key):
    """The two uint32 key words of a jax PRNG key (threefry key data).

    Works on traced keys (in-program draw) and concrete ones (host
    replay); the bits are backend-independent, which is what keeps the
    host mirror exact.
    """
    kd = jnp.ravel(jax.random.key_data(part_key))
    return kd[0].astype(jnp.uint32), kd[1].astype(jnp.uint32)


def overdraw_block(k: int, n: int) -> int:
    """Fixed over-draw block size for the hashed draw's rejection buffer.

    Sized so ONE block almost always yields k distinct values: k slots,
    a constant margin, plus FOUR times the ~B^2/(2N) expected in-block
    collisions (the deliberate safety factor — a Poisson tail at 4x its
    mean is negligible, and a too-small block only costs a second loop
    iteration, never correctness). The SELECTION is block-size
    independent ("first k distinct of the stream"), so this only tunes
    how often the fixed-shape loop iterates — capped at 4k+64 so a
    near-1 participation fraction cannot explode the in-program buffer.
    """
    if k <= 0:
        return 64
    b = k + 64
    b = k + 64 + int(4.0 * b * b / (2 * max(n, 1)))
    return max(min(b, 4 * k + 64), 1)


def _mod_limit(n: int) -> int:
    """Largest multiple of ``n`` representable in uint32 counters.

    Stream values at or above it are REJECTED before the ``% n`` so the
    kept indices are exactly uniform — a plain modulo would over-sample
    client ids below ``2**32 % n`` by ~n/2**32 relative probability
    (tiny, but systematic across every round of a long run). At most
    one value in ~4295 is rejected (n <= 2**20-ish populations), so the
    over-draw sizing is unaffected.
    """
    return (2**32 // n) * n


def _hashed_block_np(k0: np.uint32, k1: np.uint32, start: int, size: int,
                     n: int) -> np.ndarray:
    """``size`` stream positions starting at counter ``start``: exactly
    uniform int64 indices in [0, n), with modulo-bias rejections marked
    as -1 (numpy backend; the jnp path marks the same positions)."""
    ctr = np.arange(start, start + size, dtype=np.uint32)
    v0, _ = threefry2x32(np, k0, k1, ctr, np.zeros(size, np.uint32))
    vals = (v0 % np.uint32(n)).astype(np.int64)
    limit = _mod_limit(n)
    if limit < 2**32:  # n divides 2^32 exactly -> nothing to reject
        vals = np.where(v0 < np.uint32(limit), vals, -1)
    return vals


def _check_alive(alive, n: int, k: int):
    """Normalize/validate an alive mask for the masked hashed draw
    (population='dynamic', robustness/population.py): bool[n], with at
    least k alive indices — fewer could never fill a cohort and the
    first-k-distinct loop would spin forever."""
    alive = np.asarray(alive, dtype=bool)
    if alive.shape != (n,):
        raise ValueError(
            f"alive mask has shape {alive.shape}, expected ({n},)"
        )
    n_alive = int(alive.sum())
    if n_alive < k:
        raise ValueError(
            f"cannot draw a {k}-client cohort from {n_alive} alive "
            f"clients (population {n}); departures must leave at least "
            "the cohort size alive (robustness/population.py caps them)"
        )
    return alive


def hashed_cohort_np(key_words, n: int, k: int,
                     block: int | None = None,
                     alive=None) -> np.ndarray:
    """Numpy mirror of the hashed draw: first k distinct stream values.

    ``key_words`` is the uint32[>=2] key-data array
    (``np.asarray(jax.random.key_data(part_key)).ravel()``). O(k)
    expected work for k << N — the host replay path
    (``Algorithm.cohort_indices``) runs THIS, not the jitted loop,
    because at cohort=256 the draw is a few microseconds of numpy and
    must never cost a device round-trip.

    ``alive`` (optional bool[n]) masks indices out of the stream — a
    DEPARTED client (population='dynamic') is rejected exactly like a
    modulo-bias value, so the cohort is the first k distinct ALIVE
    stream values and a departed index can never be resampled. With an
    all-True mask the selection is identical to the unmasked draw (the
    static-until-first-event bit-identity contract); the jitted
    :func:`hashed_cohort` applies the same rejection, so the two
    backends stay element-for-element equal by construction. A mostly-
    dead population only costs extra rejection loop iterations, never a
    different selection.
    """
    if not 0 < k <= n:
        raise ValueError(f"need 0 < k <= n, got k={k}, n={n}")
    if alive is not None:
        alive = _check_alive(alive, n, k)
    kw = np.asarray(key_words).ravel()
    k0, k1 = np.uint32(kw[0]), np.uint32(kw[1])
    size = block or overdraw_block(k, n)
    out = np.empty(k, dtype=np.int64)
    count = 0
    start = 0
    while count < k:
        vals = _hashed_block_np(k0, k1, start, size, n)
        start += size
        if alive is not None:
            # Departed indices are rejected like modulo-bias values (the
            # -1 sentinel); np.where keeps the -1 rows out of the fancy
            # index.
            vals = np.where(
                (vals >= 0) & alive[np.where(vals >= 0, vals, 0)],
                vals, -1,
            )
        # First occurrence within the block, in stream order...
        _, first = np.unique(vals, return_index=True)
        keep = np.zeros(vals.size, dtype=bool)
        keep[first] = True
        # ... minus modulo-bias rejections (-1) and anything already
        # selected in earlier blocks.
        keep &= vals >= 0
        keep &= ~np.isin(vals, out[:count])
        fresh = vals[keep][: k - count]
        out[count : count + fresh.size] = fresh
        count += fresh.size
    return out


def hashed_cohort(part_key, n: int, k: int, block: int | None = None,
                  alive=None):
    """Jitted hashed draw: int32[k] cohort, identical to the numpy
    mirror element-for-element (same stream, same first-k-distinct
    selection; the fixed-shape ``lax.while_loop`` only changes where
    the rejection runs, never what is selected). ``alive`` (optional
    bool[n] — may be a traced operand) rejects departed indices exactly
    like :func:`hashed_cohort_np` does, so the masked draw keeps the
    jit==numpy equality contract."""
    if not 0 < k <= n:
        raise ValueError(f"need 0 < k <= n, got k={k}, n={n}")
    k0, k1 = _key_words(part_key)
    alive_j = None
    if alive is not None:
        if not isinstance(alive, jax.core.Tracer):
            # Concrete masks get the same feasibility check as the
            # numpy mirror: with fewer than k alive indices the
            # fixed-shape while_loop's `count < k` condition could
            # never flip and the program would spin forever on device —
            # raise here instead.
            _check_alive(np.asarray(alive), n, k)
        alive_j = jnp.asarray(alive, bool)
    size = block or overdraw_block(k, n)
    arange_b = jnp.arange(size, dtype=jnp.uint32)
    zeros_b = jnp.zeros(size, jnp.uint32)

    def cond(state):
        _, count, _ = state
        return count < k

    limit = _mod_limit(n)

    def body(state):
        sel, count, start = state
        v0, _ = threefry2x32(jnp, k0, k1, start + arange_b, zeros_b)
        vals = (v0 % jnp.uint32(n)).astype(jnp.int32)
        if limit < 2**32:
            # Modulo-bias rejection, mirroring the numpy path: stream
            # values past the largest multiple of n are marked -1 (the
            # trace-time gate drops the compare entirely when n divides
            # 2^32).
            vals = jnp.where(v0 < jnp.uint32(limit), vals, -1)
        if alive_j is not None:
            # Departed-index rejection (population='dynamic'), the same
            # sentinel the numpy mirror uses; the clip keeps the -1
            # sentinel rows from indexing out of bounds.
            vals = jnp.where(
                (vals >= 0) & alive_j[jnp.clip(vals, 0)], vals, -1
            )
        # Stream-order first occurrence within the block: a value is a
        # duplicate if an EARLIER position holds it (strict lower
        # triangle of the equality matrix — O(B^2) compares on a small
        # fixed block, trivially cheap next to a training round).
        eq = vals[:, None] == vals[None, :]
        dup_within = jnp.tril(eq, -1).any(axis=1)
        # ... and against every value selected in earlier blocks (the
        # -1 sentinel rows never match a valid index).
        seen = (vals[:, None] == sel[None, :]).any(axis=1)
        fresh = (vals >= 0) & (~dup_within) & (~seen)
        rank = jnp.cumsum(fresh) - 1 + count
        take = fresh & (rank < k)
        # Scatter the taken values at their ranks; everything else
        # lands on the k-th dummy slot (dropped by the final slice).
        pos = jnp.where(take, rank, k)
        sel = sel.at[pos].set(vals)
        return sel, count + jnp.sum(take), start + jnp.uint32(size)

    sel0 = jnp.full(k + 1, -1, dtype=jnp.int32)
    sel, _, _ = jax.lax.while_loop(
        cond, body, (sel0, jnp.asarray(0, jnp.int32), jnp.uint32(0))
    )
    return sel[:k]


def draw_cohort(part_key, n_clients: int, n_participants: int,
                sampler: str = "exact", alive=None):
    """In-program cohort draw — the one entry the round program traces.

    ``exact`` is byte-for-byte the pre-feature
    ``jax.random.choice(replace=False)`` (the bit-identity pin);
    ``hashed`` is the O(cohort) keyed-hash draw. Both return the
    cohort's true client ids with a leading axis of ``n_participants``.
    ``alive`` (hashed only — config.validate() pins the pairing) masks
    departed indices out of the stream (population='dynamic').
    """
    if sampler == "exact":
        if alive is not None:
            raise ValueError(
                "participation_sampler='exact' cannot compose an alive "
                "mask: the permutation draw has no maskable stream; use "
                "'hashed' for dynamic populations"
            )
        return jax.random.choice(
            part_key, n_clients, (n_participants,), replace=False
        )
    if sampler == "hashed":
        return hashed_cohort(part_key, n_clients, n_participants,
                             alive=alive)
    raise ValueError(
        f"unknown participation_sampler {sampler!r}; known: "
        + ", ".join(SAMPLERS)
    )


def draw_cohort_host(part_key, n_clients: int, n_participants: int,
                     sampler: str = "exact", *,
                     key_words=None, alive=None) -> np.ndarray:
    """Host replay of :func:`draw_cohort` (``Algorithm.cohort_indices``)
    — the ONE host entry for both modes.

    ``exact`` runs the SAME ``jax.random.choice`` (jax PRNG draws are
    backend-deterministic, so the CPU replay is the in-program draw
    bit-for-bit — the PR 7 discipline, at its O(N log N) cost);
    ``hashed`` runs the numpy mirror in O(cohort) — no full-N work, no
    full-N memory, which is what flips the million-client stream leg
    from host-bound to model-bound. ``key_words`` optionally supplies
    the part_key's raw uint32 words for the hashed path (callers that
    derive them through a jitted split chain —
    ``fedavg._hashed_part_key_words`` — pass them here so the hashed
    composition itself still lives in exactly one place; ``part_key``
    may then be None).
    """
    if sampler == "exact":
        if alive is not None:
            raise ValueError(
                "participation_sampler='exact' cannot compose an alive "
                "mask: the permutation draw has no maskable stream; use "
                "'hashed' for dynamic populations"
            )
        return np.asarray(
            jax.random.choice(
                part_key, n_clients, (n_participants,), replace=False
            )
        )
    if sampler == "hashed":
        if key_words is None:
            key_words = np.asarray(jax.random.key_data(part_key)).ravel()
        return hashed_cohort_np(key_words, n_clients, n_participants,
                                alive=alive)
    raise ValueError(
        f"unknown participation_sampler {sampler!r}; known: "
        + ", ".join(SAMPLERS)
    )
