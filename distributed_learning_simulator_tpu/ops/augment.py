"""In-step data augmentation, fixed-shape and jit-friendly.

The reference delegates dataset transforms to its external trainer
(``dataset_collection.transform_dataset``, reference simulator.py:20-22 —
the L1 surface in SURVEY §2.4). Here augmentation is a pure batched op
applied inside the training step after shard decode, so it fuses into the
round program: fresh randomness every step, zero host involvement, no
recompilation (shapes never change).

``cifar_augment``: the standard CIFAR recipe — random horizontal flip +
pad-4 random crop, vectorized over the batch with per-sample offsets.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_PAD = 4


def cifar_augment(x: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Random flip + pad-4 random crop on an NHWC batch, per-sample RNG.

    Padding rows/cols are zeros (the dataset's [0, 1] range makes zero the
    natural fill). Returns the same shape and dtype as the input.
    """
    b, h, w, c = x.shape
    flip_key, crop_key = jax.random.split(key)

    flip = jax.random.bernoulli(flip_key, 0.5, (b,))
    x = jnp.where(flip[:, None, None, None], x[:, :, ::-1, :], x)

    pad = jnp.pad(x, ((0, 0), (_PAD, _PAD), (_PAD, _PAD), (0, 0)))
    offsets = jax.random.randint(crop_key, (b, 2), 0, 2 * _PAD + 1)

    def crop_one(img, off):
        return jax.lax.dynamic_slice(img, (off[0], off[1], 0), (h, w, c))

    return jax.vmap(crop_one)(pad, offsets)


_AUGMENTS = {"cifar": cifar_augment}


def get_augment(name: str | None):
    """Augment registry: name -> fn(batch, key) -> batch; 'none'/None -> None."""
    if not name or name.lower() in ("none", ""):
        return None
    key = name.lower()
    if key not in _AUGMENTS:
        raise ValueError(
            f"unknown augmentation {name!r}; known: none, {sorted(_AUGMENTS)}"
        )
    return _AUGMENTS[key]
