"""Cohort gather/scatter over the client axis — the ONE copy.

Every algorithm that samples, regroups, or streams clients needs the same
three index operations over client-stacked pytrees:

  * :func:`cohort_take` — gather rows of every leaf at ``idx`` (the
    participation-sampling gather in ``fedavg.round_fn`` and the
    size-aware scheduler's per-group slice — previously two ad-hoc
    ``take = lambda a: jnp.take(a, idx, axis=0)`` copies);
  * :func:`cohort_scatter` — write cohort rows back into the full stack
    at ``idx`` (per-client state / metrics scatter);
  * :func:`batched_take` — per-row gather ``out[c] = a[c, idx[c]]``
    (sign_SGD's per-step minibatch gather over the client axis).

Both residency modes (``config.client_residency``) go through these: the
resident round program gathers on device, and the streamed host store
(data/residency.py) mirrors the same index math in numpy — keeping the
two implementations semantically paired is what the bit-identity
contract between the modes rests on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cohort_take(tree, idx):
    """Gather rows ``idx`` along axis 0 of every leaf of ``tree``.

    ``tree`` may be a bare array or any pytree (per-client state); None
    leaves (absent momentum buffers) pass through untouched.
    """
    return jax.tree_util.tree_map(lambda a: jnp.take(a, idx, axis=0), tree)


def cohort_scatter(tree, idx, update):
    """Write cohort rows ``update`` back into ``tree`` at rows ``idx``.

    The inverse of :func:`cohort_take` for state that persists across
    rounds: non-selected rows keep their values. ``idx`` must be
    duplicate-free (participation sampling draws without replacement).
    """
    return jax.tree_util.tree_map(
        lambda full, part: full.at[idx].set(part), tree, update
    )


def batched_take(stacked, idx):
    """Per-row gather: ``out[c] = stacked[c, idx[c]]`` for each client c.

    ``stacked`` is ``[C, S, ...]``, ``idx`` is ``[C, B]``; returns
    ``[C, B, ...]`` — each client's own minibatch rows from its own shard.
    """
    return jax.vmap(lambda a, i: jnp.take(a, i, axis=0))(stacked, idx)
