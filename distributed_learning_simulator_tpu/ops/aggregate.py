"""Aggregation ops over the client axis.

Each function here replaces a reference *server class* hot loop with a pure
function over client-stacked pytrees (every leaf has leading dim = n_clients):

  * :func:`weighted_mean` — dataset-size-weighted FedAvg aggregation
    (reference servers/fed_server.py:44-66,81: per-tensor weighted sum over N
    buffered client param dicts).
  * :func:`subset_weighted_mean` — weighted average over an arbitrary client
    subset given as a 0/1 mask, empty subset falling back to the previous
    global model (reference servers/fed_server.py:44-47 ``get_subset_model``,
    the Shapley workhorse). Mask form makes the op fixed-shape, so thousands
    of subsets batch under ``vmap`` (reference instead loops Python subsets,
    multiround_shapley_value_server.py:34-40).

On a sharded client axis these reductions are lowered by XLA to ICI
collectives — the TPU-native equivalent of the reference's queue
barrier + broadcast (servers/fed_server.py:75-91).
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np


def weighted_mean(stacked_tree, weights):
    """Weighted average over the leading (client) axis of every leaf.

    ``weights`` is ``[n_clients]`` (e.g. per-client dataset sizes, parity with
    fed_server.py:58-66); they are normalized internally.
    """
    weights = jnp.asarray(weights, dtype=jnp.float32)
    # All-zero weights (e.g. a sampled cohort of only empty Dirichlet
    # clients) must not produce NaN; the caller decides the fallback
    # (round_fn keeps the previous global model, parity with
    # fed_server.py:45-47's empty-subset behavior).
    w = weights / jnp.maximum(jnp.sum(weights), 1e-12)
    return jax.tree_util.tree_map(
        lambda x: jnp.tensordot(w.astype(x.dtype), x, axes=(0, 0)), stacked_tree
    )


def subset_weighted_mean(stacked_tree, weights, mask, fallback_tree):
    """Weighted average over the clients selected by ``mask`` (0/1, [n_clients]).

    Empty subset returns ``fallback_tree`` (the previous global model), parity
    with reference fed_server.py:45-47. Fixed-shape in ``mask``, so it can be
    ``vmap``-ed over a batch of subset masks for Shapley evaluation.
    """
    weights = jnp.asarray(weights, dtype=jnp.float32)
    mask = jnp.asarray(mask, dtype=jnp.float32)
    mw = weights * mask
    total = jnp.sum(mw)
    safe_total = jnp.where(total > 0, total, 1.0)
    norm = mw / safe_total
    nonempty = total > 0

    def _leaf(x, fb):
        # preferred_element_type: accumulate in f32 even when the stack is
        # read in bf16 (shapley_eval_dtype) — the MXU's native
        # bf16-in/f32-out contraction; a no-op for f32 stacks. The weight
        # vector itself stays f32 (ADVICE r5): tensordot handles the mixed
        # operand dtypes, the vector is tiny, and rounding the normalized
        # weights to bf16 would perturb every coordinate of the mean.
        avg = jnp.tensordot(
            norm, x, axes=(0, 0), preferred_element_type=jnp.float32,
        )
        return jnp.where(nonempty, avg, fb.astype(avg.dtype))

    return jax.tree_util.tree_map(_leaf, stacked_tree, fallback_tree)


def block_prefix_cumsum(stacked_tree, weights, perm_block,
                        carry_tree=None, carry_total=None):
    """Weighted running sums over a block of permutation positions.

    The GTG-Shapley cumsum path (``gtg_prefix_mode='cumsum'``): instead of
    one mask-weighted reduction over the FULL ``[n_clients, ...]`` stack per
    permutation prefix (O(N*P) bytes each, O(N^2*P) per walk), gather only
    the block's clients in permutation order and extend a running weighted
    sum — every prefix aggregate of the walk costs O(P) gathered bytes, and
    an eps-truncated walk never touches the clients past its stopping block.

    ``perm_block`` is ``[G, B]`` int32 client ids: for each of G
    permutations, the clients at walk positions ``[j0, j0+B)``.
    ``carry_tree`` / ``carry_total`` (leaves ``[G, ...]`` / ``[G]``, f32)
    hold the running sums over positions ``[0, j0)``; None = the block
    starts the walk. Returns ``(cs_tree, totals)`` with leaves
    ``[G, B, ...]`` / ``[G, B]`` in f32 — accumulation is f32 regardless of
    the stack dtype (a bf16 running sum over hundreds of clients would
    swallow the small late terms) — where ``cs_tree[g, b]`` is
    ``sum_{k <= j0+b} w[perm_g[k]] * x[perm_g[k]]``.
    """
    weights = jnp.asarray(weights, dtype=jnp.float32)
    w = weights[perm_block]  # [G, B]
    totals = jnp.cumsum(w, axis=1)
    if carry_total is not None:
        totals = totals + carry_total[:, None]

    def _leaf(x, c):
        xg = x[perm_block].astype(jnp.float32)  # [G, B, ...] gather
        wexp = w.reshape(w.shape + (1,) * (x.ndim - 1))
        cs = jnp.cumsum(xg * wexp, axis=1)
        if c is not None:
            cs = cs + c[:, None]
        return cs

    if carry_tree is None:
        cs_tree = jax.tree_util.tree_map(lambda x: _leaf(x, None), stacked_tree)
    else:
        cs_tree = jax.tree_util.tree_map(_leaf, stacked_tree, carry_tree)
    return cs_tree, totals


def prefix_means_from_cumsum(cs_tree, totals, fallback_tree):
    """Prefix aggregates from running sums: ``cs / total`` where the prefix
    carries weight, the fallback model (previous global params) where it
    does not — the same zero-weight semantics as
    :func:`subset_weighted_mean`'s empty-subset branch. Leaves come back
    ``[G, B, ...]`` f32, matching the masked path's f32 subset models.
    """
    nonempty = totals > 0
    safe = jnp.where(nonempty, totals, 1.0)

    def _leaf(cs, fb):
        shape = totals.shape + (1,) * (cs.ndim - 2)
        avg = cs / safe.reshape(shape)
        return jnp.where(nonempty.reshape(shape), avg, fb.astype(avg.dtype))

    return jax.tree_util.tree_map(_leaf, cs_tree, fallback_tree)


def coordinate_median(stacked_tree, weights=None):
    """Coordinate-wise median over the client axis (Byzantine-robust).

    Robust-aggregation extension beyond the reference (its weighted mean,
    fed_server.py:58-66, is the only aggregator there — yet its own
    heterogeneity experiment injects a poisoned client,
    simulator_backup.py:71-77). The statistic itself is unweighted (a median
    has no meaningful per-client weighting), but ``weights`` are used as a
    participation mask: clients with ``weights[i] <= 0`` (empty Dirichlet
    shards under ``max_shard_size`` padding return the broadcast params
    bit-identical) are excluded from the per-coordinate statistic so they
    cannot vote the aggregate back toward the previous model. If the whole
    cohort is zero-weight, the unmasked median is returned (every row IS
    the broadcast model, so that median equals the previous model — the
    correct stall).
    """
    valid = None
    if weights is not None:
        valid = jnp.asarray(weights, jnp.float32) > 0
        # All-zero-weight cohort: treat every client as valid so the single
        # statistic below degrades to the unmasked median (one nanmedian per
        # leaf either way — both jnp.where branches would execute under jit,
        # doubling the sort cost of every robust round).
        valid = valid | ~jnp.any(valid)

    def _leaf(x):
        # nanmedian: a poisoned client whose local training diverged to NaN
        # must not poison the aggregate (jnp.median would propagate it).
        xf = x.astype(jnp.float32)
        if valid is not None:
            vshape = (-1,) + (1,) * (x.ndim - 1)
            xf = jnp.where(valid.reshape(vshape), xf, jnp.nan)
        return jnp.nanmedian(xf, axis=0).astype(x.dtype)

    return jax.tree_util.tree_map(_leaf, stacked_tree)


_TRIM_SCALE = 10_000  # trim ratios quantized to 1e-4 (see trim_count)


def trim_count(m, trim_ratio: float):
    """``floor(m * trim_ratio)`` with the ratio quantized to 1e-4, in ONE
    integer formula shared by the static unweighted path, the traced
    weighted path, Krum's Byzantine count, and config-time validation.

    Why not plain float: the same ratio rounds differently in float32
    (the traced path) and float64 (Python) — e.g. 0.29 * 100 is 28.999...
    in f64 (int -> 28) but can land at 29.000001 in f32 (floor -> 29), so
    two code paths would silently trim different client counts for the
    same configuration. Integer math keeps every site in lockstep; the
    scale stays small enough that ``m * q`` fits int32 for any realistic
    cohort (m <= ~200k). The quantization itself FLOORS (not rounds): a
    half-up quantize would let trim_ratio just under 0.5 reach q = SCALE/2
    and empty the trim window (m - 2k = 0) for even cohorts, breaking the
    ``trim_ratio < 0.5  =>  m - 2k >= 1`` invariant the validation check
    relies on.
    """
    q = int(trim_ratio * _TRIM_SCALE)
    if isinstance(m, int):
        return (m * q) // _TRIM_SCALE
    return (m.astype(jnp.int32) * q) // _TRIM_SCALE


def trimmed_mean(stacked_tree, trim_ratio: float, weights=None):
    """Coordinate-wise trimmed mean: drop the k lowest and k highest values
    per coordinate (k = floor(trim_ratio * m), m = participating clients,
    computed by :func:`trim_count`), average the rest.

    Byzantine-robust for up to k adversarial clients. ``trim_ratio`` is
    static (part of the compiled program). Like :func:`coordinate_median`,
    ``weights`` act as a participation mask: zero-weight clients are
    excluded from the per-coordinate order statistic (they are bit-identical
    copies of the broadcast model, not updates); with an all-zero cohort the
    unmasked statistic is returned. NaN uploads sort into the trimmed top
    region as long as the per-coordinate NaN count stays <= k; beyond that
    the result goes NaN and the round-level finite-or-previous fallback
    engages.
    """
    n_total = jax.tree_util.tree_leaves(stacked_tree)[0].shape[0]
    if not 0.0 <= trim_ratio < 0.5:
        # trim_ratio < 0.5 also guarantees m - 2k >= 1 for any participating
        # count m >= 1 in the weighted path below (k = floor(trim_ratio*m)),
        # so no runtime empty-window case exists past this check.
        raise ValueError(
            f"trim_ratio {trim_ratio} removes all {n_total} clients"
        )
    if weights is None:

        def _leaf(x):
            n = x.shape[0]
            k = trim_count(n, trim_ratio)
            if trim_ratio > 0.0:
                # Same at-least-one-trim clamp as the weighted path below.
                k = min(max(k, 1), (n - 1) // 2)
            s = jnp.sort(x.astype(jnp.float32), axis=0)
            kept = s[k : n - k] if k else s
            return jnp.mean(kept, axis=0).astype(x.dtype)

        return jax.tree_util.tree_map(_leaf, stacked_tree)

    valid = jnp.asarray(weights, jnp.float32) > 0
    # All-zero-weight cohort: treat every client as valid — the statistic
    # degrades to the unmasked trimmed mean with one sort per leaf (a
    # second jnp.where branch would double the sort cost of every round).
    valid = valid | ~jnp.any(valid)
    m = jnp.sum(valid.astype(jnp.int32))
    # k from the RUNTIME participating count, clamped: validate() only
    # guarantees k >= 1 for the configured cohort, but m can shrink below
    # it at round time (empty Dirichlet shards) until trim_count floors to
    # 0 — a plain mean with zero robustness that a single finite-but-huge
    # Byzantine upload would shift arbitrarily. Keep at least one trim
    # whenever a ratio was asked for AND the window survives
    # (k <= (m-1)//2 keeps m - 2k >= 1; for m <= 2 no trim is possible).
    k = trim_count(m, trim_ratio)
    if trim_ratio > 0.0:
        k = jnp.clip(jnp.maximum(k, 1), 0, (m - 1) // 2)

    def _leaf_w(x):
        n = x.shape[0]
        xf = x.astype(jnp.float32)
        vshape = (-1,) + (1,) * (x.ndim - 1)
        idx = jnp.arange(n).reshape(vshape)
        masked = jnp.where(valid.reshape(vshape), xf, jnp.nan)
        s = jnp.sort(masked, axis=0)  # valid values first, NaN rows last
        keep = (idx >= k) & (idx < m - k)
        kept_sum = jnp.sum(jnp.where(keep, s, 0.0), axis=0)
        return (kept_sum / (m - 2 * k)).astype(x.dtype)

    return jax.tree_util.tree_map(_leaf_w, stacked_tree)


def krum(stacked_tree, n_byzantine: int = 0, weights=None):
    """Krum (Blanchard et al.): select the single client update closest to
    its n - f - 2 nearest neighbors (f = assumed Byzantine count).

    Robust to f colluding adversaries whose updates are far from the honest
    cluster. Two classes of degenerate candidates are masked out of both the
    candidate set and everyone's neighbor lists:

      * non-finite uploads (local training diverged to NaN/inf), and
      * zero-weight clients (``weights[i] <= 0``, e.g. empty Dirichlet
        shards) — these return the broadcast params bit-identical, so two
        of them would otherwise win the closest-pair score with distance 0
        and freeze the global model.

    Masked entries use a large FINITE sentinel distance (an inf/NaN sentinel
    would corrupt the score sums they appear in). O(n^2 * P); the [n, P]
    flattened stack must fit in HBM.
    """
    leaves = jax.tree_util.tree_leaves(stacked_tree)
    n = leaves[0].shape[0]
    if n < 2 * n_byzantine + 3:
        # Below this bound (Blanchard et al.), f colluding identical uploads
        # can have pairwise distance 0 and win the closest-neighbor score.
        raise ValueError(
            f"krum needs n >= 2f + 3 clients (n={n}, assumed Byzantine "
            f"f={n_byzantine}); lower trim_ratio or add clients"
        )
    flat = [leaf.reshape(n, -1).astype(jnp.float32) for leaf in leaves]
    bad = jnp.zeros((n,), dtype=bool)
    for row in flat:
        bad = bad | ~jnp.all(jnp.isfinite(row), axis=1)
    if weights is not None:
        bad = bad | (jnp.asarray(weights, jnp.float32) <= 0.0)
    x = jnp.concatenate([jnp.nan_to_num(row, nan=0.0) for row in flat], axis=1)
    sq = jnp.sum(x * x, axis=1)
    d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (x @ x.T), 0.0)
    big = jnp.float32(1e30)
    masked = bad[:, None] | bad[None, :] | jnp.eye(n, dtype=bool)
    d2 = jnp.where(masked, big, d2)
    k = max(1, min(n - n_byzantine - 2, n - 1))
    nearest = jnp.sort(d2, axis=1)[:, :k]
    # The extra bad-penalty keeps masked clients out of argmin even in the
    # degenerate all-sentinel case (more masked clients than k neighbors).
    scores = jnp.sum(nearest, axis=1) + bad.astype(jnp.float32) * big * n
    best = jnp.argmin(scores)
    return jax.tree_util.tree_map(lambda leaf: leaf[best], stacked_tree)


def aggregate(stacked_tree, weights, rule: str, trim_ratio: float = 0.1):
    """Dispatch over the aggregation rules (single source of truth for the
    vmap fast path and the thread-per-client server).

    For ``krum``, ``trim_ratio`` doubles as the assumed Byzantine fraction
    (f = floor(trim_ratio * n_clients)).
    """
    rule = rule.lower()
    if rule == "median":
        return coordinate_median(stacked_tree, weights=weights)
    if rule == "trimmed_mean":
        return trimmed_mean(stacked_tree, trim_ratio, weights=weights)
    if rule == "krum":
        n = jax.tree_util.tree_leaves(stacked_tree)[0].shape[0]
        return krum(stacked_tree, n_byzantine=trim_count(n, trim_ratio),
                    weights=weights)
    if rule == "mean":
        return weighted_mean(stacked_tree, weights)
    raise ValueError(
        f"unknown aggregation {rule!r}; known: mean, median, trimmed_mean, "
        "krum"
    )


def subset_masks_all(n_clients: int, include_empty: bool = True) -> np.ndarray:
    """All 2^N subset masks as a ``[2^N, N]`` 0/1 array (host-side helper).

    Replaces the reference's ``powerset`` iterator
    (servers/shapley_value_server.py:11-14) with a fixed-shape mask batch for
    ``vmap``. Row order: subsets sorted by (size, lexicographic), empty first.
    """
    ids = list(range(n_clients))
    rows = []
    for r in range(0 if include_empty else 1, n_clients + 1):
        for combo in itertools.combinations(ids, r):
            row = np.zeros((n_clients,), dtype=np.float32)
            row[list(combo)] = 1.0
            rows.append(row)
    return np.stack(rows)
