from distributed_learning_simulator_tpu.ops.aggregate import (
    weighted_mean,
    subset_weighted_mean,
    subset_masks_all,
)
from distributed_learning_simulator_tpu.ops.sign import sign_compress, majority_vote
from distributed_learning_simulator_tpu.ops.quantize import (
    stochastic_quantize,
    dequantize,
    stochastic_quantize_tree,
    dequantize_tree,
    fake_quant,
    fake_quant_tree,
)
from distributed_learning_simulator_tpu.ops.payload import (
    payload_bytes,
    quantized_payload_bytes,
    sign_payload_bytes,
    compression_ratio,
)
from distributed_learning_simulator_tpu.ops.sampling import (
    draw_cohort,
    draw_cohort_host,
    hashed_cohort,
    hashed_cohort_np,
)

__all__ = [
    "weighted_mean",
    "subset_weighted_mean",
    "subset_masks_all",
    "sign_compress",
    "majority_vote",
    "stochastic_quantize",
    "dequantize",
    "stochastic_quantize_tree",
    "dequantize_tree",
    "fake_quant",
    "fake_quant_tree",
    "payload_bytes",
    "quantized_payload_bytes",
    "sign_payload_bytes",
    "compression_ratio",
    "draw_cohort",
    "draw_cohort_host",
    "hashed_cohort",
    "hashed_cohort_np",
]
