"""Simulation orchestrator: the TPU-native ``simulator.py``.

Replaces the reference entry point (reference simulator.py:33-72): where the
reference builds a thread pool, a queue-owning server, and one worker thread
per client, this builds

  dataset -> client partition (packed client axis) -> model/optimizer ->
  algorithm strategy -> ONE jitted round function -> host round loop.

The host loop only sequences rounds, evaluates the global model once per
round (parity with fed_server.py:85-86), logs, checkpoints, and runs the
algorithm's host-side post_round hook (Shapley). All training compute for all
clients in a round is a single XLA program launch.

Multi-chip: set ``config.mesh_devices`` — the packed client arrays and
per-client state get ``PartitionSpec("clients")`` over a 1-D mesh and the
same program runs SPMD; weighted-mean/vote reductions become ICI collectives.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import signal
import threading
import time
import zlib
from collections.abc import Mapping
from contextlib import ExitStack, contextmanager

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.algorithms.base import RoundContext
from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.partition import (
    ClientData,
    dirichlet_partition,
    iid_partition,
    pack_client_shards,
)
from distributed_learning_simulator_tpu.data.registry import Dataset, get_dataset
from distributed_learning_simulator_tpu.data.residency import HostShardStore
from distributed_learning_simulator_tpu.factory import get_algorithm
from distributed_learning_simulator_tpu.models.registry import get_model, init_params
from distributed_learning_simulator_tpu.parallel.engine import (
    make_batched_round_fn,
    make_decoder,
    make_eval_fn,
    make_optimizer,
    make_reshaper,
    make_streamed_batched_round_fn,
    pad_eval_set,
)
from distributed_learning_simulator_tpu.parallel.mesh import (
    make_mesh,
    replicate,
    shard_client_data,
)
from distributed_learning_simulator_tpu.parallel.streaming import (
    CohortStreamer,
)
from distributed_learning_simulator_tpu.robustness.arrivals import (
    AsyncFederation,
)
from distributed_learning_simulator_tpu.robustness.chaos import maybe_crash
from distributed_learning_simulator_tpu.robustness.population import (
    PopulationModel,
    pop_key_words,
)
from distributed_learning_simulator_tpu.telemetry import (
    ClientStats,
    ClientValuation,
    RecompileMonitor,
    SpanPhaseTimer,
    SpanRecorder,
    ValuationAuditor,
    ValuationState,
    clock,
    costmodel_record,
    detect_and_record,
    hbm_limit_bytes,
    ledger_totals,
    log_round_compiles,
    make_phase_timer,
    peak_hbm_bytes,
    valuation_record,
)
from distributed_learning_simulator_tpu.utils.reporting import (
    build_round_record,
)
from distributed_learning_simulator_tpu.utils.errors import is_device_oom
from distributed_learning_simulator_tpu.utils.checkpoint import (
    gc_checkpoints,
    latest_checkpoint,
    load_latest_valid_checkpoint,
    save_checkpoint,
)
from distributed_learning_simulator_tpu.utils.logging import (
    get_logger,
    set_level,
    set_run_artifacts,
)
from distributed_learning_simulator_tpu.utils.tracing import (
    annotate,
    categorize_ops,
    profile_session,
)


def _f32_param_bytes(global_params) -> int:
    """f32 bytes of one model's params (works on arrays or ShapeDtypeStructs)."""
    return sum(
        leaf.size * 4 for leaf in jax.tree_util.tree_leaves(global_params)
    )


def _device_budget_bytes(config) -> float:
    """Usable device memory for per-client state: 60% of per-device HBM
    times the mesh size (the client axis is split across mesh devices);
    16 GB fallback when the plugin doesn't report memory stats. The ONE
    copy of the budget model shared by the chunk auto-sizer, the OOM hint,
    and the materializing-path feasibility refusal."""
    hbm = hbm_limit_bytes() or 16 * 1024**3
    return 0.6 * hbm * (config.mesh_devices or 1)


def _persistent_state_factor(config) -> int:
    """Param-sized persistent per-client buffers: one per client for
    momentum sign_SGD or a persistent sgd optimizer, two for persistent
    adam. The one copy shared by the chunk auto-sizer and the residency
    feasibility check."""
    if (
        config.distributed_algorithm == "sign_SGD"
        and config.momentum != 0.0
    ):
        return 1
    if not config.reset_client_optimizer:
        return 2 if config.optimizer_name.lower() in ("adam", "adamw") else 1
    return 0


def _resident_clients(config, n_clients: int) -> int:
    """How many clients' persistent arrays are DEVICE-resident at once:
    the whole population under client_residency='resident', only the
    sampled cohort under 'streamed' (the host shard store owns the rest;
    data/residency.py)."""
    if config.client_residency.lower() == "streamed":
        return config.cohort_size(n_clients)
    return n_clients


def _auto_chunk_size(config, global_params, n_clients: int) -> int:
    """In-flight clients from the footprint model shared with the OOM
    diagnostics (_oom_hint derives its suggestion from this function):
    ~4x the f32 param bytes of transient state per in-flight client
    (grads + momentum + conv weight-grad temps incl. fragmentation)
    against the _device_budget_bytes budget, minus any PERSISTENT
    per-client state that is resident regardless of chunking
    (momentum-sign_SGD buffers, non-reset client optimizer state) — at
    POPULATION size when resident, cohort size under streamed residency
    (the budget the streaming layer exists to change).
    Validated on v5e: suggests ~57 for ResNet-18 x 1000 clients, inside
    the measured-safe 40-100 range."""
    param_bytes = _f32_param_bytes(global_params)
    budget = (
        _device_budget_bytes(config)
        - _persistent_state_factor(config)
        * _resident_clients(config, n_clients) * param_bytes
    )
    estimate = max(1, int(budget / (4 * param_bytes)))
    return min(estimate, config.cohort_size(n_clients))


def _assert_residency_feasible(config, global_params, n_clients: int,
                               data_bytes: int) -> None:
    """Refuse clearly when the per-client arrays cannot fit the device.

    Under the resident default every per-client array — the packed data
    shards AND any persistent algorithm state — is a device-resident
    ``[n_clients, ...]`` stack for the whole run; when that footprint
    exceeds the budget the run used to die as an opaque allocation
    failure deep inside the first dispatch. Name the fix instead:
    ``client_residency='streamed'`` keeps the full-N arrays in the host
    shard store and sizes HBM by the cohort (x2 for the double-buffered
    prefetch), which is what this check verifies in streamed mode.
    """
    budget = _device_budget_bytes(config)
    param_bytes = _f32_param_bytes(global_params)
    factor = _persistent_state_factor(config)
    streamed = config.client_residency.lower() == "streamed"
    if streamed:
        cohort = config.cohort_size(n_clients)
        per_client_data = data_bytes / max(n_clients, 1)
        # Sampled regime: two cohorts in flight — the computing
        # dispatch's slice plus the prefetched next one
        # (parallel/streaming.py double buffering). Full-cohort regime
        # (cohort == N, e.g. sign_SGD): ONE startup upload, resident
        # thereafter — no second buffer to budget for.
        buffers = 2 if cohort < n_clients else 1
        total = buffers * cohort * per_client_data + (
            factor * cohort * param_bytes
        )
        if total > budget:
            buf_note = (
                f"{buffers} (double-buffered) x " if buffers > 1
                else "1 (full-cohort, one startup upload) x "
            )
            raise ValueError(
                "client_residency='streamed' cohort footprint does not "
                f"fit: {buf_note}{cohort} cohort clients x "
                f"{per_client_data / 2**20:.1f} MB data + {factor} "
                f"param-sized state buffer(s) x {param_bytes / 2**20:.0f} "
                f"MB = {total / 2**30:.1f} GB, over the "
                f"~{budget / 2**30:.1f} GB device budget. Lower "
                "participation_fraction (the cohort) or raise "
                "mesh_devices (streamed residency shards the cohort "
                "slice over the mesh)."
            )
        return
    total = data_bytes + factor * n_clients * param_bytes
    if total > budget:
        state_note = (
            f" + {factor} param-sized state buffer(s) x {n_clients} "
            f"clients x {param_bytes / 2**20:.0f} MB"
            if factor else ""
        )
        raise ValueError(
            "client_residency='resident' keeps every per-client array "
            f"device-resident: {data_bytes / 2**30:.1f} GB of packed "
            f"data shards{state_note} = {total / 2**30:.1f} GB, over "
            f"the ~{budget / 2**30:.1f} GB device budget "
            f"({config.mesh_devices or 1} device(s)). Set "
            "client_residency='streamed' to keep the population host-side "
            "and stream only the sampled cohort, or use more mesh_devices."
        )


def _host_client_state(algorithm, optimizer, global_params, n_clients: int):
    """Full-N per-client state on the HOST (streamed residency).

    ``init_client_state`` builds a device stack — exactly what a
    million-client run must not do. Every init in the tree is
    per-client IDENTICAL (vmapped ``optimizer.init`` / broadcast
    zeros), so one client's row replicated N times is the same state
    the resident path would gather — the property the bit-identity
    contract between the residency modes rests on.
    """
    proto = algorithm.init_client_state(optimizer, global_params, 1)
    if proto is None:
        return None
    proto = jax.device_get(proto)
    return jax.tree_util.tree_map(
        lambda a: np.repeat(np.asarray(a), n_clients, axis=0), proto
    )


def _owned_device_tree(tree):
    """Device-place a host tree with buffers XLA exclusively owns.

    ``jnp.asarray`` of a numpy array is zero-copy on the CPU backend, so
    feeding the result to a ``donate_argnums`` position lets XLA write
    into (and free) memory the host side still holds — intermittent NaN
    histories or a hard interpreter abort depending on heap layout.
    Every host-originated tree that reaches a donated argnum (resumed
    client/server state, streamed state gathers) must go through here.
    """
    return jax.tree_util.tree_map(lambda a: jnp.array(a, copy=True), tree)


def _lr_factor(config, round_idx: int) -> float:
    """Per-round lr multiplier from config.lr_schedule (host-side scalar,
    passed into the jitted round program — no retrace across rounds)."""
    s = config.lr_schedule.lower()
    if s == "constant":
        return 1.0
    horizon = config.lr_schedule_rounds or config.round
    if s == "cosine":
        progress = min(round_idx / max(horizon - 1, 1), 1.0)
        return config.lr_min_factor + (1.0 - config.lr_min_factor) * 0.5 * (
            1.0 + math.cos(math.pi * progress)
        )
    # "step" (validate() guarantees the name set)
    return config.lr_step_gamma ** (round_idx // config.lr_step_size)


def lr_factors(config, start: int, k: int) -> np.ndarray:
    """Schedule factors for rounds ``start .. start+k-1`` as one f32 vector.

    The single source for BOTH dispatch shapes: the host loop's per-round
    scalar is ``lr_factors(config, r, 1)[0]`` and the batched dispatch
    (config.rounds_per_dispatch > 1) passes the whole vector as the scan
    operand — same _lr_factor values through the same f32 cast, so the
    two programs see bit-identical schedule operands.
    """
    return np.asarray(
        [_lr_factor(config, start + i) for i in range(k)], dtype=np.float32
    )


def build_base_round_record(config, round_idx: int, metrics: dict,
                            fetched_loss, fetched_tel: dict, extra: dict,
                            round_seconds: float) -> dict:
    """The v1-layout base of one round's metrics record — fields AND
    insert order. The ONE copy shared by ``run_simulation``'s
    emit_record and the sweep engine's lean/fleet loops
    (sweep/engine.py), so a sweep point's records can never drift from
    solo metrics.jsonl lines. ``extra`` is the algorithm's post_round
    dict (non-scalar values filtered exactly as before);
    ``round_seconds`` is the caller's wall attribution (between-round
    wall solo; the amortized dispatch share in a fleet)."""
    record = {
        "round": round_idx,
        "test_accuracy": metrics["accuracy"],
        "test_loss": metrics["loss"],
        "mean_client_loss": float(fetched_loss),
        "round_seconds": round_seconds,
        **{
            k: v for k, v in extra.items()
            if isinstance(v, (int, float, dict))
        },
    }
    if config.lr_schedule.lower() != "constant":
        record["lr_factor"] = _lr_factor(config, round_idx)
    if "survivor_count" in fetched_tel:
        record["survivor_count"] = int(fetched_tel["survivor_count"])
    if "round_rejected" in fetched_tel:
        record["round_rejected"] = bool(fetched_tel["round_rejected"])
    if "participants" in fetched_tel:
        # CRC of the sampled cohort: a compact per-round fingerprint
        # that lets the resume-determinism tests assert the cohort
        # sampling stream survives checkpoint/resume bit-exactly
        # without bloating metrics.jsonl with index lists.
        record["cohort_hash"] = zlib.crc32(
            np.ascontiguousarray(
                fetched_tel["participants"], dtype=np.int64
            ).tobytes()
        )
    return record


#: Per-round async-federation scalars the round program reports in aux
#: (robustness/arrivals.py; the carried ``async_state`` itself is popped
#: before any record building). Fetched inside the round's single metric
#: device_get, rendered as the schema-v4 ``async`` sub-object.
_ASYNC_AUX_KEYS = (
    "on_time_count", "late_count", "buffer_count", "buffer_applied",
    "mean_staleness", "sim_duration", "sim_duration_sync", "sim_clock",
)


class _StackedAuxRow(Mapping):
    """Lazy per-round view of a batched dispatch's scan-stacked aux.

    RoundContext.aux promises per-round device arrays, but no
    batching-capable algorithm's post_round reads aux today — slicing
    every stacked leaf eagerly would dispatch K x leaves tiny gather ops
    per dispatch on exactly the host path round batching exists to
    shrink. Leaves are sliced only on access."""

    __slots__ = ("_aux_k", "_i")

    def __init__(self, aux_k: dict, i: int):
        self._aux_k = aux_k
        self._i = i

    def __getitem__(self, name):
        return self._aux_k[name][self._i]

    def __iter__(self):
        return iter(self._aux_k)

    def __len__(self):
        return len(self._aux_k)


def _algo_checkpoint_state(algorithm, metrics, server_state,
                           async_state=None, valuation=None,
                           population=None) -> dict:
    """Assemble the checkpoint's ``algo_state`` dict — the ONE copy shared
    by the round-loop checkpoint cadence, the batched-dispatch flush, and
    the SIGTERM force-write path (the copies were one field away from
    drifting). ``async_state`` is the staleness-buffer carry
    (robustness/arrivals.py) — persisted so an async resume replays the
    buffer bit-exactly, absent entirely for synchronous runs.
    ``valuation`` is the streaming per-client valuation vector
    (telemetry/valuation.py) — persisted so a resumed run keeps its
    accumulated contribution evidence; absent when the feature is off.
    ``population`` is the dynamic-population registration-stream payload
    (robustness/population.PopulationModel.checkpoint_state: cursor +
    alive mask + joined shard rows) — what makes a resume mid-growth
    stitch bit-identically; absent for static populations."""
    algo_state = {"prev_metrics": metrics}
    if hasattr(algorithm, "shapley_values"):
        algo_state["shapley_values"] = algorithm.shapley_values
    if server_state is not None:
        algo_state["server_opt_state"] = jax.device_get(server_state)
    if async_state is not None:
        algo_state["async_state"] = jax.device_get(async_state)
    if valuation is not None:
        algo_state["valuation"] = np.asarray(valuation)
    if population is not None:
        algo_state["population"] = population
    return algo_state


def _assert_client_stack_feasible(config, global_params, n_clients: int):
    """Refuse the materializing path clearly when it cannot fit.

    Algorithms whose ``materializes_client_stack`` is true (Shapley scoring,
    client_eval telemetry, robust aggregation rules) hold the FULL
    ``[n_clients, params]`` f32 stack resident —
    chunking bounds the training transients, not this stack. At large N x
    large model that dies as a generic device OOM deep inside dispatch;
    mirror MultiRoundShapley's explicit N>16 refusal with a sized error
    instead (same footprint/budget model as _auto_chunk_size)."""
    param_bytes = _f32_param_bytes(global_params)
    # The round program stacks only the SAMPLED cohort (fedavg.round_fn
    # trains n_participants clients), so that is what must fit.
    cohort = config.cohort_size(n_clients)
    stack_bytes = cohort * param_bytes
    # GTG's cumsum prefix walk (gtg_prefix_mode='cumsum') additionally
    # carries one f32 running-sum row per still-active permutation. Worst
    # case THREE stack-sized carry trees coexist at a wave boundary (the
    # previous wave's carry, its compacted gather, and the re-concatenated
    # outputs — _CumsumPrefixWalker.eval_block), so budget the stack 3x
    # over — reported as its own term so the message's arithmetic is the
    # arithmetic checked: the whole point of this check is a clear,
    # size-your-config-from-it refusal instead of a generic OOM mid-walk.
    carry_note = ""
    total_bytes = stack_bytes
    if (
        config.distributed_algorithm == "GTG_shapley_value"
        and getattr(config, "gtg_prefix_mode", "cumsum") == "cumsum"
    ):
        total_bytes = 3 * stack_bytes
        carry_note = (
            " plus up to 2 stack-sized cumsum-walk carry trees = "
            f"{total_bytes / 2**30:.1f} GB peak"
        )
    budget = _device_budget_bytes(config)
    if total_bytes > budget:
        raise ValueError(
            f"{config.distributed_algorithm!r} materializes the per-client "
            f"parameter stack: {cohort} clients x "
            f"{param_bytes / 2**20:.0f} MB = {stack_bytes / 2**30:.1f} GB"
            f"{carry_note}, "
            f"over the ~{budget / 2**30:.1f} GB device budget "
            f"({config.mesh_devices or 1} device(s)). Use fewer clients, a "
            "smaller model, or more mesh_devices."
        )


@contextmanager
def _oom_hint(config, global_params, n_clients: int, site: str = "round"):
    """Re-raise device OOMs with an actionable client_chunk_size suggestion.

    Wraps every point where an async-dispatched round can surface a
    RESOURCE_EXHAUSTED error (dispatch, eval, and the deferred metric fetch
    — with async dispatch an execution-time OOM appears at the next host
    sync, not necessarily at the call that caused it).

    Footprint model (measured on v5e): ~4x the f32 param bytes per
    in-flight client (grads + momentum + conv weight-grad temps, incl.
    fragmentation); budget 60% of per-device HBM times the mesh size (the
    chunk is split across mesh devices); 16 GB fallback when the plugin
    doesn't report memory stats.
    """
    try:
        yield
    except jax.errors.JaxRuntimeError as e:
        if not is_device_oom(e):
            raise
        # In-flight clients = chunk bounded by the sampled cohort size.
        cohort = config.cohort_size(n_clients)
        current = min(config.client_chunk_size or cohort, cohort)
        eval_note = (
            f" This OOM surfaced at {site}: if lowering client_chunk_size "
            f"doesn't help, also lower eval_batch_size "
            f"(currently {config.eval_batch_size})."
            if site != "round" else ""
        )
        param_bytes = _f32_param_bytes(global_params)
        estimate = _auto_chunk_size(config, global_params, n_clients)
        suggestion = min(estimate, max(1, current // 2))
        if suggestion >= current:
            raise RuntimeError(
                "device memory exceeded even with "
                f"client_chunk_size={current}; the model "
                f"(~{param_bytes / 2**20:.0f} MB of params) may not fit this "
                "device — use a smaller model or more mesh devices."
                + eval_note
            ) from e
        raise RuntimeError(
            "device memory exceeded with "
            f"{current} clients in flight (per-client params/grads/momentum "
            "and activations scale with client_chunk_size). Try "
            f"client_chunk_size={suggestion}." + eval_note
        ) from e


def build_client_data(config: ExperimentConfig, dataset: Dataset) -> ClientData:
    """Partition the training set into the packed client axis."""
    if config.partition == "iid":
        indices = iid_partition(
            len(dataset.x_train), config.worker_number, seed=config.seed
        )
    else:
        indices = dirichlet_partition(
            dataset.y_train, config.worker_number, config.dirichlet_alpha,
            seed=config.seed,
        )
    if config.max_shard_size:
        # Unbiased cap: partition index lists are dataset-ordered, so a
        # plain [:cap] would keep only low-index samples (dropping whole
        # classes on class-ordered datasets).
        rng = np.random.default_rng(config.seed + 17)
        indices = [
            rng.permutation(ix)[: config.max_shard_size] for ix in indices
        ]
    return pack_client_shards(
        dataset.x_train, dataset.y_train, indices,
        batch_size=config.batch_size,
        compact=config.compact_client_data,
    )


def run_simulation(
    config: ExperimentConfig,
    dataset: Dataset | None = None,
    client_data: ClientData | None = None,
    setup_logging: bool = True,
):
    """Run the full federated simulation; returns a result dict.

    ``dataset``/``client_data`` injection points cover the reference's
    heterogeneous-data variant (simulator_backup.py:71-77): build
    ``client_data`` yourself, call ``client_data.override_client(0, ...)``,
    and pass it in.
    """
    config.validate()
    # Cross-host clock alignment for span journals (telemetry/spans.py):
    # zeros for single-process runs; estimated once right after the
    # jax.distributed init barrier when tracing is on (the one moment
    # every host is provably inside the same code region).
    span_on = config.span_trace.lower() == "on"
    span_clock_offset = 0.0
    span_clock_unc = 0.0
    if config.multihost:
        # Before ANY device query or dispatch: jax.distributed must come up
        # first so the default backend enumerates every host's devices.
        from distributed_learning_simulator_tpu.parallel.multihost import (
            estimate_clock_alignment,
            initialize_multihost,
        )

        initialize_multihost(
            coordinator_address=config.coordinator_address,
            num_processes=config.num_processes,
            process_id=config.process_id,
        )
        if span_on:
            span_clock_offset, span_clock_unc = estimate_clock_alignment()
    # Compilation-cache config comes BEFORE the execution-mode dispatch so
    # threaded runs (whose per-client local_train is jitted too) get the
    # persistent cache as well.
    if config.compilation_cache_dir:
        jax.config.update(
            "jax_compilation_cache_dir", config.compilation_cache_dir
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    else:
        # The setting is process-global; reset so a cache enabled by an
        # earlier run in this process doesn't leak into a run that asked
        # for no caching.
        jax.config.update("jax_compilation_cache_dir", None)
    if config.execution_mode.lower() == "threaded":
        if config.multihost:
            # The thread-per-client mode has no multi-process awareness;
            # each process would independently train ALL clients and write
            # a full artifact set — the silent split initialize_multihost's
            # contract forbids.
            raise ValueError(
                "execution_mode='threaded' does not support multihost; "
                "use the vmap execution mode"
            )
        # Honor the flag from EVERY entry point (heterogeneous CLI, bench,
        # programmatic callers), not just simulator.main.
        from distributed_learning_simulator_tpu.execution.threaded import (
            run_threaded_simulation,
        )

        return run_threaded_simulation(
            config, dataset=dataset, client_data=client_data,
            setup_logging=setup_logging,
        )
    logger = get_logger()
    set_level(config.log_level)
    # Multi-process SPMD runs one identical program per process; artifacts
    # (log file, metrics.jsonl, checkpoints) are written by process 0 only
    # — every process writing the same timestamped paths would interleave
    # log lines, duplicate every metrics record, and race checkpoint
    # writes into torn files.
    is_primary = jax.process_index() == 0
    log_dir = None
    if setup_logging and not is_primary:
        setup_logging = False
    if setup_logging:
        # Per-run artifact dir: Shapley metric pickles etc. go here so
        # concurrent/subsequent runs never overwrite each other's artifacts.
        log_path, log_dir = set_run_artifacts(
            config.log_root, config.distributed_algorithm,
            config.dataset_name, config.model_name,
        )
        logger.info("log file: %s", log_path)

    # --- data ---------------------------------------------------------------
    if dataset is None:
        dataset = get_dataset(
            config.dataset_name, data_dir=config.data_dir, seed=config.seed,
            n_train=config.n_train, n_test=config.n_test,
            **config.dataset_args,
        )
    if client_data is None:
        client_data = build_client_data(config, dataset)
    n_clients = client_data.n_clients
    # Flat eval storage + in-program reshape: see make_reshaper's TPU
    # layout note (explicit NHWC input buffers pad 3-channel lanes to 128).
    eval_batches_np = pad_eval_set(
        dataset.x_test, dataset.y_test, config.eval_batch_size, flatten=True
    )
    eval_preprocess = make_reshaper(dataset.x_test.shape[1:])

    # --- model / optimizer / algorithm --------------------------------------
    model = get_model(
        config.model_name, num_classes=dataset.num_classes,
        **config.model_args,
    )
    global_params = init_params(model, dataset.x_train[:1], seed=config.seed)
    if config.client_chunk_size == 0:  # auto
        # Resolve into a LOCAL copy: writing back to the caller's config
        # would freeze this model's footprint-derived chunk into an object
        # the caller may reuse with a different model (where auto should
        # re-resolve). The resolved value is logged and in the result dict.
        config = dataclasses.replace(
            config,
            client_chunk_size=_auto_chunk_size(
                config, global_params, n_clients
            ),
        )
        logger.info(
            "auto client_chunk_size=%d (footprint model, %s params)",
            config.client_chunk_size, config.model_name,
        )
    optimizer = make_optimizer(
        config.optimizer_name, config.learning_rate,
        momentum=config.momentum, weight_decay=config.weight_decay,
    )
    algorithm = get_algorithm(config.distributed_algorithm, config)
    # Client-state residency (config.client_residency; data/residency.py +
    # parallel/streaming.py). 'resident' (default) keeps every per-client
    # array device-resident — the exact pre-feature program. 'streamed'
    # keeps the full-N arrays in a host shard store and uploads only the
    # sampled cohort per dispatch, double-buffered so the next dispatch's
    # cohort transfers while the current one computes.
    streamed = config.client_residency.lower() == "streamed"
    if streamed and not getattr(
        algorithm, "supports_streamed_residency", False
    ):
        raise ValueError(
            f"algorithm {config.distributed_algorithm!r} does not support "
            "client_residency='streamed': its round program assumes a "
            "device-resident per-client stack (the Shapley family's "
            "subset re-evaluation); set client_residency='resident'"
        )
    cohort_n = config.cohort_size(n_clients)
    # Sampling regime: per-dispatch cohort upload + prefetch + writeback.
    # Full-cohort regime (participation_fraction >= 1, e.g. sign_SGD):
    # the "cohort" is everyone — one startup upload, then the loop runs
    # the resident program shape (HBM already sizes by the cohort).
    stream_sampled = streamed and cohort_n < n_clients
    stream_full = streamed and not stream_sampled
    # Distributed shard store (streamed x multihost; data/residency.py +
    # parallel/streaming.DistributedCohortStreamer): with >1 host
    # process, each process owns an N/num_hosts client slice and serves
    # its own members of every round's owner-permuted cohort straight
    # into its addressable shards of the client-axis PartitionSpec.
    # Everything below is gated on mh, so a single process — including
    # multihost=True in a 1-process environment — runs the exact
    # single-host streamed path (the num_hosts==1 zero-cost contract).
    n_procs = jax.process_count()
    mh = streamed and config.multihost and n_procs > 1
    mh_mesh = None
    mh_owner_bounds = None
    mh_block_bounds = None
    if mh:
        from distributed_learning_simulator_tpu.data.residency import (
            host_axis_bounds,
        )
        from distributed_learning_simulator_tpu.parallel.multihost import (
            mesh_devices_per_host,
        )

        # The mesh is needed BEFORE placement here: ownership bounds
        # derive from its per-host device split, and the sharded-
        # checkpoint resume path validates the manifest against them.
        mh_mesh = make_mesh(config.mesh_devices)
        devs_per_host = mesh_devices_per_host(mh_mesh)
        mh_owner_bounds = host_axis_bounds(n_clients, devs_per_host)
        if stream_sampled:
            if cohort_n % config.mesh_devices != 0:
                raise ValueError(
                    "cohort size (participation_fraction x "
                    f"worker_number) ({cohort_n}) must be a multiple "
                    f"of mesh_devices ({config.mesh_devices})"
                )
            mh_block_bounds = host_axis_bounds(cohort_n, devs_per_host)
        else:
            # Full-cohort regime: the upload axis IS the client axis, so
            # ownership bounds and block bounds coincide.
            mh_block_bounds = mh_owner_bounds
    # Open-world population (config.population; robustness/population.py):
    # None at the 'static' default — the exact pre-feature path. Under
    # 'dynamic' the registration stream owns joins/departures/drift; the
    # cohort stays PINNED at this startup population's sampled size
    # (cohort_n), so the compiled round program never changes shape
    # while N grows. config.validate() already pinned the composition
    # (streamed + hashed + sampled + FedAvg family).
    pop = PopulationModel.from_config(
        config, n_clients, cohort_n, dataset=dataset
    )
    if pop is not None and not stream_sampled:
        raise ValueError(
            "population='dynamic' needs a sampled streamed cohort "
            f"(cohort {cohort_n} of {n_clients} clients is the whole "
            "population at this worker_number); raise worker_number or "
            "lower participation_fraction"
        )
    _assert_residency_feasible(
        config, global_params, n_clients,
        client_data.x.nbytes + client_data.y.nbytes
        + client_data.mask.nbytes + client_data.sizes.nbytes,
    )
    if algorithm.materializes_client_stack:
        _assert_client_stack_feasible(config, global_params, n_clients)
    if config.lr_schedule.lower() != "constant" and not getattr(
        algorithm, "supports_lr_schedule", False
    ):
        # Capability lives on the Algorithm class, not a config-level name
        # list: a third-party algorithm whose round_fn lacks the lr_scale
        # operand must fail HERE with the cause, not with an arity
        # TypeError at the first round dispatch.
        raise ValueError(
            f"algorithm {config.distributed_algorithm!r} does not support "
            "lr_schedule (its round program takes no lr_scale operand)"
        )
    if config.rounds_per_dispatch > 1 and not getattr(
        algorithm, "supports_round_batching", False
    ):
        # Same capability pattern as supports_round_pipelining, but a
        # refusal rather than a silent fallback: the user asked for a
        # different dispatch shape, and post_round hooks that must see
        # every round (Shapley's data-dependent subset evaluation) cannot
        # run inside one fused program.
        raise ValueError(
            f"algorithm {config.distributed_algorithm!r} does not support "
            "rounds_per_dispatch > 1: its post_round must observe every "
            "round (for the FedAvg family this includes client_eval=True "
            "and keep_client_params — their aux/post_round consume "
            "per-round parameter stacks); set rounds_per_dispatch=1"
        )
    # Asynchronous federation (robustness/arrivals.py): same capability
    # pattern as supports_round_batching — a refusal with the cause, not
    # a silent synchronous run the user didn't ask for.
    async_ctl = AsyncFederation.from_config(config)
    if async_ctl is not None and not getattr(
        algorithm, "supports_async", False
    ):
        raise ValueError(
            f"algorithm {config.distributed_algorithm!r} does not support "
            "async_mode='on': its round program has no staleness buffer "
            "to hold late uploads; set async_mode='off'"
        )

    # The raw eval fn is shared by the standalone jitted program (K=1
    # dispatches) and the batched dispatch, which fuses it into the
    # round scan (rounds_per_dispatch > 1).
    eval_fn = make_eval_fn(
        model.apply, preprocess=eval_preprocess, name="server_eval"
    )
    evaluate = jax.jit(eval_fn)
    algorithm.prepare(
        model.apply, make_eval_fn(model.apply, preprocess=eval_preprocess)
    )
    preprocess = (
        make_decoder(client_data.sample_shape) if client_data.compact else None
    )
    # Static per-client sample counts feed the size-aware work scheduler
    # (FedAvg fused path); withheld under mesh/multihost sharding, where the
    # client axis layout is owned by the PartitionSpec.
    _sharded = config.multihost or (
        config.mesh_devices is not None and config.mesh_devices > 1
    )
    # Count-dependent feasibility (exact Shapley's 2^N bound, GTG's
    # permutation cap) against the TRUE client count, for every algorithm
    # regardless of its make_round_fn inheritance (the threaded runner
    # makes the mirror call before its pool spawns).
    algorithm.check_cohort(n_clients)
    round_fn = algorithm.make_round_fn(
        model.apply, optimizer, n_clients, preprocess=preprocess,
        client_sizes=None if _sharded else client_data.sizes,
    )
    if stream_full:
        # Full-cohort streamed convention differs from the resident one
        # only by the idx operand (always None — the cohort is everyone).
        # Re-adapt so the round loop (and make_batched_round_fn) runs the
        # SAME call shape as resident — which is what makes this regime
        # bit-identical by construction.
        _streamed_fn = round_fn

        def round_fn(global_params, client_state, cx, cy, cmask, sizes,
                     key, lr_scale=1.0, async_state=None):
            kw = {} if async_state is None else {"async_state": async_state}
            return _streamed_fn(
                global_params, client_state, cx, cy, cmask, sizes, None,
                key, lr_scale, **kw,
            )

    round_jit = jax.jit(round_fn, donate_argnums=(1,))

    # Optional server-side optimizer (FedOpt; exceeds the reference): the
    # aggregate is post-processed by a jitted pseudo-gradient step.
    server_state = None
    server_update_fn = None
    server_update_jit = None
    _server = algorithm.make_server_update()
    if (
        _server is None
        and config.server_optimizer_name.lower() not in ("none", "")
    ):
        # Don't let a configured server optimizer silently no-op: only the
        # FedAvg family consumes it (SignSGD applies votes inside the round).
        raise ValueError(
            f"algorithm {config.distributed_algorithm!r} does not support a "
            "server optimizer; set server_optimizer_name='none'"
        )
    if _server is not None:
        server_init, server_update_fn = _server
        server_state = server_init(global_params)
        # Donate the consumed aggregate and the replaced opt state: neither
        # is referenced after the call (entry keeps only the updated state).
        server_update_jit = jax.jit(server_update_fn, donate_argnums=(1, 2))

    # --- resume (before placement, so restored state gets sharded too) ------
    start_round = 0
    prev_metrics: dict | None = None
    # Streaming valuation vector saved by an earlier run (applied after
    # placement, once the ValuationState — and, under streamed
    # residency, its host-store home — exists).
    resumed_valuation = None
    # Dynamic-population registration-stream state saved by an earlier
    # run (applied after placement: it grows the host store by the
    # checkpointed joined shards and restores the alive mask + cursor).
    resumed_population = None
    key = jax.random.key(config.seed + 1)
    if streamed:
        # Host-side init: the full-N state tree must never be built as a
        # device stack (that allocation is what streamed mode removes).
        # Under the distributed store each host initializes ONLY the
        # rows it owns — per-host state RAM scales as N/num_hosts like
        # the data shards (every init row is identical, so the sliced
        # init equals the full init's slice by construction).
        _n_state = (
            int(mh_owner_bounds[jax.process_index() + 1]
                - mh_owner_bounds[jax.process_index()])
            if mh else n_clients
        )
        client_state = _host_client_state(
            algorithm, optimizer, global_params, _n_state
        )
    else:
        client_state = algorithm.init_client_state(
            optimizer, global_params, n_clients
        )
    # Staleness-buffer carry (async_mode='on'): one f32 param-sized
    # accumulator + scalars, owned by the host loop like client_state —
    # threaded into every dispatch, checkpointed, restored on resume.
    async_state = (
        async_ctl.init_state(global_params) if async_ctl is not None else None
    )
    if config.resume and config.checkpoint_dir:
        from distributed_learning_simulator_tpu.utils.checkpoint import (
            load_latest_valid_sharded_checkpoint,
            manifest_rounds,
            validate_manifest,
        )

        if mh:
            # Per-host shards + manifest (utils/checkpoint.py): each
            # process restores its OWN shard; the manifest commits the
            # round and records the topology the shards were cut for.
            # The shard payload carries the same keys as a whole
            # checkpoint, so every structure/config check below runs
            # unchanged on it.
            manifest, ckpt = load_latest_valid_sharded_checkpoint(
                config.checkpoint_dir, jax.process_index(), n_procs
            )
            if manifest is not None:
                validate_manifest(
                    manifest, n_hosts=n_procs, n_clients=n_clients,
                    owner_bounds=mh_owner_bounds,
                )
                # The agreement check below hashes the MANIFEST name
                # (identical across hosts); shard basenames differ per
                # host by construction.
                ckpt_path = os.path.join(
                    config.checkpoint_dir,
                    f"round_{manifest['round']}.manifest.json",
                )
            else:
                ckpt_path = None
                if latest_checkpoint(config.checkpoint_dir):
                    raise RuntimeError(
                        "multihost streamed resume found only a "
                        "single-file checkpoint in "
                        f"{config.checkpoint_dir!r}: it was written by "
                        "a single-process run and cannot be re-split "
                        "into per-host shards; resume it on the "
                        "topology it was written with"
                    )
        else:
            # Integrity-verified discovery: a corrupt/truncated latest
            # checkpoint (CRC mismatch) is skipped with a warning and
            # resume falls back to the newest VALID one instead of
            # crashing.
            ckpt_path, ckpt = load_latest_valid_checkpoint(
                config.checkpoint_dir
            )
            if ckpt_path is None and manifest_rounds(config.checkpoint_dir):
                raise RuntimeError(
                    f"checkpoint dir {config.checkpoint_dir!r} holds "
                    "per-host sharded checkpoints (a multihost streamed "
                    "run wrote them); resume under the multihost "
                    "streamed topology they were written with — this "
                    "run is "
                    + ("multihost resident"
                       if config.multihost else "single-process")
                )
        if ckpt_path:
            resumed_basename = os.path.basename(ckpt_path)
            want_gp = jax.tree_util.tree_structure(global_params)
            got_gp = jax.tree_util.tree_structure(ckpt["global_params"])
            if want_gp != got_gp:
                # Fail here with the cause, not mid-apply with a missing-
                # param error: e.g. a checkpoint written before a model's
                # internal layout change (resnet18 fold_stage1 renames its
                # block modules) or with a different model_name entirely.
                raise ValueError(
                    "checkpoint global_params do not match this model's "
                    f"parameter structure ({config.model_name!r}); the "
                    "checkpoint was written with a different model or "
                    "model version — resume with the configuration it was "
                    "written with"
                )
            global_params = jax.tree_util.tree_map(
                jnp.asarray, ckpt["global_params"]
            )
            want_cs = jax.tree_util.tree_structure(client_state)
            got_cs = jax.tree_util.tree_structure(ckpt["client_state"])
            if want_cs != got_cs:
                # e.g. a sign_SGD checkpoint written with momentum=0 has no
                # per-client buffers (client_state=None) while momentum>0
                # expects them — resuming across that mismatch would either
                # crash inside jit or silently drop the saved buffers.
                def _describe(ts) -> str:
                    n = ts.num_leaves
                    return "no per-client state" if n == 0 else (
                        f"per-client state with {n} leaves"
                    )

                raise ValueError(
                    "checkpoint client_state does not match this "
                    "configuration (e.g. momentum / reset_client_optimizer "
                    "changed since the checkpoint was written): checkpoint "
                    f"has {_describe(got_cs)}, config expects "
                    f"{_describe(want_cs)}; resume with the configuration "
                    "the checkpoint was written with"
                )
            # Streamed residency restores into the HOST shard store
            # (the source of truth between dispatches), not a device
            # stack; stream_full device-places it below. Resident state
            # is a donated round_jit operand, so it needs owned buffers.
            client_state = (
                jax.tree_util.tree_map(np.asarray, ckpt["client_state"])
                if streamed
                else _owned_device_tree(ckpt["client_state"])
            )
            start_round = ckpt["round_idx"] + 1
            prev_metrics = ckpt["algo_state"].get("prev_metrics")
            if (
                server_state is None
                and ckpt["algo_state"].get("server_opt_state") is not None
            ):
                raise ValueError(
                    "checkpoint was written with a server optimizer but "
                    "server_optimizer_name='none' now; resume with the "
                    "configuration the checkpoint was written with"
                )
            if server_state is not None:
                saved_ss = ckpt["algo_state"].get("server_opt_state")
                if saved_ss is None:
                    logger.warning(
                        "checkpoint has no server optimizer state (written "
                        "before the feature or with a different config); "
                        "server optimizer restarts from fresh state"
                    )
                else:
                    want = jax.tree_util.tree_structure(server_state)
                    got = jax.tree_util.tree_structure(saved_ss)
                    if want != got:
                        raise ValueError(
                            "checkpoint server optimizer state does not match "
                            f"server_optimizer_name="
                            f"{config.server_optimizer_name!r}; resume with "
                            "the configuration the checkpoint was written with"
                        )
                    # Donated by server_update_jit/batched dispatch.
                    server_state = _owned_device_tree(saved_ss)
            saved_async = ckpt["algo_state"].get("async_state")
            if async_ctl is None and saved_async is not None:
                raise ValueError(
                    "checkpoint was written with async_mode='on' but "
                    "async_mode='off' now (the staleness buffer would be "
                    "silently discarded); resume with the configuration "
                    "the checkpoint was written with"
                )
            if async_ctl is not None:
                if saved_async is None:
                    raise ValueError(
                        "async_mode='on' but the checkpoint has no "
                        "staleness-buffer state (written with "
                        "async_mode='off'); resume with the configuration "
                        "the checkpoint was written with"
                    )
                async_state = jax.tree_util.tree_map(jnp.asarray, saved_async)
            if ckpt.get("rng_key") is not None:
                key = ckpt["rng_key"]
            if hasattr(algorithm, "shapley_values"):
                algorithm.shapley_values.update(
                    ckpt["algo_state"].get("shapley_values", {})
                )
            resumed_valuation = ckpt["algo_state"].get("valuation")
            resumed_population = ckpt["algo_state"].get("population")
            if pop is not None and resumed_population is None:
                raise ValueError(
                    "population='dynamic' but the checkpoint has no "
                    "registration-stream state (written with "
                    "population='static'); resume with the configuration "
                    "the checkpoint was written with"
                )
            if pop is None and resumed_population is not None:
                raise ValueError(
                    "checkpoint was written with population='dynamic' "
                    "but population='static' now (the grown population "
                    "and alive mask would be silently discarded); resume "
                    "with the configuration the checkpoint was written "
                    "with"
                )
            logger.info("resumed from %s at round %d", ckpt_path, start_round)
        else:
            resumed_basename = ""
        if config.multihost and jax.process_count() > 1:
            # Checkpoints are written by process 0 only, but every process
            # restores independently from its own view of checkpoint_dir.
            # Without a shared filesystem the processes can restore
            # different rounds (or some none at all) and then dispatch
            # DIFFERENT numbers of SPMD round programs — a collective
            # mismatch (hang) or a silent split. Verify agreement before
            # any sharded dispatch; checkpoint_dir must be on storage all
            # hosts see (NFS/GCS-fuse) for multihost resume.
            from jax.experimental import multihost_utils

            local = np.asarray(
                [start_round, zlib.crc32(resumed_basename.encode())],
                dtype=np.int64,
            )
            gathered = multihost_utils.process_allgather(local)
            if not (gathered == gathered[0]).all():
                raise RuntimeError(
                    "multihost resume mismatch: processes restored "
                    "different checkpoints (per-process [start_round, "
                    f"path_crc32] = {gathered.tolist()}); checkpoint_dir "
                    "must be a shared filesystem visible to every host "
                    "with an identical checkpoint set"
                )

    # --- placement ----------------------------------------------------------
    mesh = None
    store = None
    streamer = None
    startup_stream = {"rec": None}  # stream_full's one-shot upload record
    eval_batches = tuple(jnp.asarray(a) for a in eval_batches_np)
    if config.mesh_devices and config.mesh_devices > 1:
        mesh = mh_mesh if mh_mesh is not None else make_mesh(
            config.mesh_devices
        )
        # The DEVICE-resident client-axis length must split evenly over
        # the mesh: the whole population when resident (or full-cohort
        # streamed — the startup upload IS population-shaped), but only
        # the sampled COHORT under streamed sampling, where the cohort
        # slice is the array that carries PartitionSpec("clients").
        shard_len = cohort_n if stream_sampled else n_clients
        if shard_len % config.mesh_devices != 0:
            what = (
                "cohort size (participation_fraction x worker_number)"
                if stream_sampled else "worker_number"
            )
            raise ValueError(
                f"{what} ({shard_len}) must be a multiple of "
                f"mesh_devices ({config.mesh_devices})"
            )
    if streamed:
        # Host shard store owns the full-N arrays (data/residency.py);
        # the streamer owns their device side (parallel/streaming.py) —
        # under a mesh it uploads each cohort slice directly into the
        # client-axis PartitionSpec layout. config.validate() already
        # refused multihost + threaded.
        # Dynamic populations mutate label rows in place (drift) and the
        # store normally ALIASES the caller's packed arrays
        # (ascontiguousarray is zero-copy on contiguous input) — take
        # ownership of the label array up front so a caller-shared
        # client_data (bench legs, library callers) is never corrupted
        # as a side effect. Labels only: x/mask/sizes are never mutated
        # (growth appends into separate backing buffers).
        _pop_y = (
            np.array(client_data.y, copy=True) if pop is not None
            else client_data.y
        )
        if mh:
            from distributed_learning_simulator_tpu.data.residency import (
                DistributedShardStore,
            )
            from distributed_learning_simulator_tpu.parallel.streaming import (
                DistributedCohortStreamer,
            )

            # Owner-sharded store: this process keeps ONLY its owned
            # client slice (constructor copies it out of the full-N
            # view every process derives from the deterministic
            # partition); the streamer serves those members straight
            # into this host's addressable shards of the client-axis
            # PartitionSpec. config.validate() pinned the composition
            # (hashed sampler for sampled cohorts, no dynamic
            # population / client_stats / valuation / async / K>1).
            store = DistributedShardStore(
                client_data.x, _pop_y, client_data.mask,
                client_data.sizes,
                state=client_state if stream_sampled else None,
                host_id=jax.process_index(),
                owner_bounds=mh_owner_bounds,
            )
            streamer = DistributedCohortStreamer(
                store, algorithm, n_clients, mh_mesh, mh_block_bounds
            )
            if stream_full:
                (cx, cy, cmask, _szs, _full_idx), startup_stream["rec"] = (
                    streamer.upload_full()
                )
                # sizes stays a host value: the mesh block below
                # replicates it like the resident multihost path (a
                # host array is placeable into a global sharding; the
                # upload's client-sharded sizes array is not
                # re-placeable cross-process).
                sizes = client_data.sizes
            else:
                cx = cy = cmask = None
                sizes = client_data.sizes
                client_state = None
                logger.info(
                    "distributed shard store: host %d/%d owns %d of %d "
                    "clients (%.2f GB shard), cohort %d per dispatch",
                    store.host_id, store.n_hosts, store.n_owned,
                    n_clients, store.data_bytes() / 2**30, cohort_n,
                )
        elif pop is not None and resumed_population is not None:
            # Resume mid-growth: the store starts at the startup
            # population (re-derived from the dataset partition), the
            # registration state grows it by the checkpointed joined
            # shards, and the (possibly grown) per-client state attaches
            # afterwards — lengths then agree by construction.
            store = HostShardStore(
                client_data.x, _pop_y, client_data.mask,
                client_data.sizes, state=None,
            )
            pop.restore(resumed_population, store)
            if stream_sampled and client_state is not None:
                store.attach_state(client_state)
            logger.info(
                "population resumed at cursor %d: %d registered, %d "
                "alive (%d joined, %d departed)",
                pop.cursor, pop.n_registered, int(pop.alive.sum()),
                pop.totals["joins"], pop.totals["departs"],
            )
        else:
            store = HostShardStore(
                client_data.x, _pop_y, client_data.mask,
                client_data.sizes,
                state=client_state if stream_sampled else None,
            )
        if not mh:
            streamer = CohortStreamer(store, algorithm, n_clients,
                                      mesh=mesh)
            if stream_full:
                (cx, cy, cmask, sizes, _full_idx), startup_stream["rec"] = (
                    streamer.upload_full()
                )
                if client_state is not None:
                    # Full-cohort state lives on device across rounds
                    # exactly like resident (the whole population IS the
                    # cohort); it is a donated round_jit operand, so
                    # copy on placement.
                    client_state = _owned_device_tree(client_state)
            else:
                # Sampled regime: no full-N device arrays exist; the
                # cohort slices are per-dispatch operands. The loop's
                # client_state stays None — the store owns the state
                # between dispatches.
                cx = cy = cmask = None
                sizes = jnp.asarray(client_data.sizes)
                client_state = None
                logger.info(
                    "client_residency='streamed': %d clients "
                    "host-resident (%.2f GB), cohort %d per dispatch",
                    n_clients, store.data_bytes() / 2**30, cohort_n,
                )
    else:
        data_arrays = (
            jnp.asarray(client_data.x), jnp.asarray(client_data.y),
            jnp.asarray(client_data.mask),
        )
        sizes = jnp.asarray(client_data.sizes)
    if mesh is not None:
        if not streamed:
            data_arrays = shard_client_data(data_arrays, mesh)
        # stream_full's population arrays were already uploaded sharded
        # by the streamer; stream_sampled has no full-N device arrays.
        # Persistent client state (resident or full-cohort streamed) is
        # client-axis sharded like the data; stream_sampled's state is
        # None here (the host store owns it — the per-round cohort
        # gather is sharded at dispatch time in the round loop).
        client_state = shard_client_data(client_state, mesh)
        global_params = replicate(global_params, mesh)
        if server_state is not None:
            server_state = replicate(server_state, mesh)
        if async_state is not None:
            # Replicated like the global model: the buffer is server-side
            # state, and the late-row reduction over the sharded client
            # axis resolves to the same replicated tree on every device.
            async_state = replicate(async_state, mesh)
        sizes = replicate(sizes, mesh)
        eval_batches = replicate(eval_batches, mesh)
        logger.info("client axis sharded over %d devices", config.mesh_devices)
    if not streamed:
        cx, cy, cmask = data_arrays

    # --- round loop ---------------------------------------------------------
    history: list[dict] = []
    metrics_path = None
    if log_dir:
        metrics_path = os.path.join(log_dir, "metrics.jsonl")

    # Pipelined mode defers each round's device->host metric fetch until the
    # NEXT round has been dispatched, so transfer latency (a full RTT when
    # the chip sits behind a network tunnel) overlaps device compute. Results
    # are bit-identical to the synchronous path — only fetch timing moves.
    # Not used when post_round must see metrics in the same round (Shapley),
    # nor when checkpointing needs per-client or server-optimizer state (those
    # buffers are donated to round r+1's dispatch before round r's deferred
    # checkpoint would read them).
    # Sharded checkpoints (distributed shard store): EVERY process
    # writes its own shard — only the manifest commit (and the legacy
    # single-file path) stays primary-only — so the flag must agree
    # across hosts (it also feeds the pipelining decision, which under
    # SPMD must resolve identically on every process).
    checkpointing = bool(
        config.checkpoint_dir and config.checkpoint_every
        and (is_primary or mh)
    )
    # Round batching (config.rounds_per_dispatch > 1): K rounds fuse into
    # one scan dispatch with one metric fetch each; pipelining's
    # deferred-fetch trick is subsumed (the dispatch itself overlaps the
    # per-round fetches it absorbed), so the two modes don't compose.
    K = config.rounds_per_dispatch
    batched = K > 1
    if batched and stream_sampled and store.state is not None:
        # Cohorts inside one fused dispatch may overlap, and a scan
        # iteration cannot scatter into the host store mid-dispatch —
        # round r+1's gathered state slice would miss round r's update.
        raise ValueError(
            "client_residency='streamed' with rounds_per_dispatch > 1 "
            "does not compose with persistent per-client state "
            "(reset_client_optimizer=False / momentum sign_SGD under "
            "sampling): cohorts within one dispatch may overlap and the "
            "host store cannot be updated mid-dispatch; set "
            "rounds_per_dispatch=1 or client_residency='resident'"
        )
    # Streamed residency with persistent per-client state: the per-round
    # writeback (a device_get of the cohort state) already syncs every
    # round, so a deferred metric fetch hides nothing — and a deferred
    # finalize would checkpoint the LIVE host store after the next
    # round's writeback mutated it.
    stream_stateful = (
        stream_sampled and store is not None and store.state is not None
    )
    pipelined = (
        config.pipeline_rounds
        and not batched
        and not stream_stateful
        and pop is None
        and algorithm.supports_round_pipelining
        and not (
            checkpointing
            and (client_state is not None or server_state is not None)
        )
    )
    if config.pipeline_rounds and not pipelined:
        # The user asked for pipelining; say out loud why it is off (each
        # deferred fetch otherwise silently costs a full host-link RTT).
        if batched:
            reason = (
                "rounds_per_dispatch > 1 already amortizes the fetch "
                "(one device_get per dispatch)"
            )
        elif pop is not None:
            reason = (
                "population='dynamic' registration events mutate host "
                "population state at every round boundary; a deferred "
                "finalize would checkpoint the wrong stream cursor"
            )
        elif stream_stateful:
            reason = (
                "streamed residency's per-round state writeback already "
                "syncs with the dispatch (nothing left to hide)"
            )
        elif not algorithm.supports_round_pipelining:
            reason = "the algorithm's post_round must see each round's metrics"
        else:
            reason = (
                "checkpointing needs per-client/server-optimizer state "
                "that round r+1's dispatch would donate away"
            )
        logger.info("pipeline_rounds disabled: %s", reason)
    t_start = time.perf_counter()
    t_prev_done = t_start
    pending: dict | None = None
    # Robustness telemetry (docs/ROBUSTNESS.md): per-round survivor counts
    # and quorum rejections, accumulated for the result dict so callers
    # (and bench.py) can't silently trade robustness for speed.
    telemetry = {
        "rounds_rejected": 0,
        "survivor_counts": [],
        # Async federation (robustness/arrivals.py): simulated-clock sums
        # (async vs the wait-for-everyone counterfactual) and the
        # buffer-occupancy trail — the result dict's async_speedup_ratio.
        "sim_async_s": 0.0,
        "sim_sync_s": 0.0,
        "buffer_occupancy": [],
    }
    # Run telemetry (telemetry/; docs/OBSERVABILITY.md): phase timing,
    # recompile counting, HBM watermark. At the default 'off' both hooks
    # are inert and the metrics records stay in the legacy v1 layout.
    tel_level = config.telemetry_level.lower()
    phase_timer = make_phase_timer(tel_level)
    recompile = RecompileMonitor() if tel_level != "off" else None
    post_warmup_compiles = {"count": 0} if recompile is not None else None
    # Distributed tracing (telemetry/spans.py): the per-host span
    # recorder + its journal, and the SpanPhaseTimer proxy that makes
    # every phase boundary a span at ANY telemetry_level. None at the
    # default 'off' — the exact pre-feature program (off-gate contract).
    span_recorder = None
    if span_on:
        span_recorder = SpanRecorder(
            host_id=jax.process_index(), n_hosts=jax.process_count(),
            capacity=config.span_buffer_size,
            flush_last_k=config.span_flush_last_k,
        )
        span_journal_dir = config.span_dir or log_dir
        if span_journal_dir:
            logger.info(
                "span journal: %s (clock offset %+.6fs ± %.6fs vs host 0)",
                span_recorder.attach(
                    span_journal_dir, span_clock_offset, span_clock_unc
                ),
                span_clock_offset, span_clock_unc,
            )
        else:
            # Non-primary hosts have no artifacts dir; without span_dir
            # the ring still works as a pure in-memory flight recorder,
            # but nothing persists — say so rather than silently drop.
            logger.warning(
                "span_trace='on' but this host has no artifacts dir and "
                "no span_dir; span journal disabled (in-memory flight "
                "recorder only) — set span_dir to a shared directory"
            )
        phase_timer = SpanPhaseTimer(phase_timer, span_recorder)
        if streamer is not None:
            streamer.span_recorder = span_recorder
            streamer.clock_offset_s = span_clock_offset
    # Per-client statistics (telemetry/client_stats.py): the round program
    # computes the [N, S] stats matrix in-program when on; the host fetches
    # it on the client_stats_every cadence inside the round's single metric
    # device_get, runs the median/MAD detector, and folds the result into
    # the schema-v3 record. None at the default 'off'.
    client_stats_cfg = ClientStats.from_config(config)
    telemetry["clients_flagged"] = 0
    # Dynamic population (robustness/population.py): rounds rejected by
    # the quorum policy where the round ALSO lost cohort members to
    # departures — the churn-collision telemetry the records flag as
    # rejected_by_churn.
    telemetry["churn_rejected"] = 0
    # One-row per-client state proto for joiners (stateful streamed
    # runs: reset_client_optimizer=False): replicated per joined client
    # by PopulationModel.apply. None for the stateless default.
    pop_state_proto = None
    if pop is not None and store is not None and store.state is not None:
        pop_state_proto = _host_client_state(
            algorithm, optimizer, global_params, 1
        )
    # Always-on client valuation (telemetry/valuation.py): the round
    # program emits a per-cohort streaming score vector (riding the
    # client-stats machinery); the host scales it by the server
    # loss-delta and folds it into the persistent exponentially-decayed
    # per-client valuation vector — a host numpy [N] array (attached to
    # the streamed host store when one exists, so the store stays the
    # one owner of full-N arrays), scatter-updated per cohort and
    # checkpointed in algo_state. On the sparse valuation_audit_every
    # cadence the auditor cross-validates the vector against a truncated
    # GTG walk over the round's exact re-materialized uploads. None at
    # the default 'off' — records stay at schema v6 or below.
    valuation_cfg = ClientValuation.from_config(config)
    vstate = None
    auditor = None
    telemetry["valuation_last_audit"] = None
    if valuation_cfg is not None:
        # Population-indexed: sized by the (possibly resumed-grown)
        # store under streamed residency so valued ids stay TRUE indices
        # across dynamic-population growth; the vector keeps growing
        # with the store (HostShardStore.grow appends zeros).
        vstate = ValuationState(
            store.n_clients if store is not None else n_clients,
            store=store,
        )
        if resumed_valuation is not None:
            vstate.load(resumed_valuation)
        elif start_round > 0:
            logger.warning(
                "checkpoint carries no valuation vector (written before "
                "the feature or with client_valuation='off'); valuation "
                "restarts from zero"
            )
        if valuation_cfg.audit_every > 0:
            auditor = ValuationAuditor(
                config, valuation_cfg, algorithm, model.apply, optimizer,
                preprocess,
                make_eval_fn(model.apply, preprocess=eval_preprocess),
                client_data, eval_batches, n_clients,
            )
    # Predictive cost model (telemetry/costmodel.py): parse the reference
    # trace ONCE at startup (pure host-side gzip read); the roofline
    # prediction attaches to the run's LAST metrics record (schema v6)
    # with this run's measured steady round time as the anchor. None at
    # the default cost_model_trace=None — records stay at v5 or below.
    cost_ledger = None
    if config.cost_model_trace:
        cost_ledger = categorize_ops(config.cost_model_trace)
        if not cost_ledger or ledger_totals(cost_ledger)["bytes_gb"] <= 0:
            # Same degrade rule as bench.py's costmodel leg: CPU traces
            # carry no raw_bytes_accessed, and a zero-byte ledger
            # predicts nothing — warn, never fabricate a $0 record.
            logger.warning(
                "cost_model_trace %r holds no byte-annotated device-op "
                "events; cost model disabled for this run",
                config.cost_model_trace,
            )
            cost_ledger = None
    telemetry["costmodel"] = None

    def _save_sharded_checkpoint(round_idx, new_global, client_state_rows,
                                 algo_state, rng_key) -> None:
        """Per-host checkpoint shards + manifest (distributed shard
        store; utils/checkpoint.py). EVERY process writes its shard —
        its owned per-client state slice plus the replicated global
        state, so each shard restores its process without cross-host
        reads — then all processes barrier on the round (the shard
        allgather doubles as the agreement check) and process 0 commits
        the round by writing the manifest. A host that dies between its
        shard write and the barrier leaves the round manifest-less:
        resume falls back one checkpoint interval, the torn-write
        discipline at shard granularity."""
        from jax.experimental import multihost_utils

        from distributed_learning_simulator_tpu.parallel.multihost import (
            allgather_wall_stamps,
        )
        from distributed_learning_simulator_tpu.utils.checkpoint import (
            gc_sharded_checkpoints,
            save_shard_checkpoint,
            shard_checkpoint_path,
            write_manifest,
        )

        pid = jax.process_index()
        save_shard_checkpoint(
            config.checkpoint_dir, round_idx, pid, n_procs,
            {
                "global_params": jax.device_get(new_global),
                "client_state": (
                    None if client_state_rows is None
                    else jax.tree_util.tree_map(
                        np.asarray, client_state_rows
                    )
                ),
                "algo_state": algo_state,
                "rng_key": jax.device_get(
                    jax.random.key_data(rng_key)
                ),
            },
            span_recorder=span_recorder,
        )
        if span_recorder is not None:
            # Checkpoint-barrier skew: a tiny aligned-arrival allgather
            # ahead of the agreement barrier — its wall is dominated by
            # the slowest host's shard write, and the gathered stamps
            # are the round's measured ckpt_skew_ms. Flight-recorder
            # eager: a host stuck here during a peer's death leaves its
            # open-line on disk. The skew is parked as pending (this
            # round's record already shipped) and rides the next one.
            wid = span_recorder.begin(
                "ckpt_barrier_wait", "dcn_wait", round_idx=round_idx,
                eager=True,
            )
            stamps = allgather_wall_stamps(
                clock.wall() - span_clock_offset
            )
            skew_ms = float(stamps.max() - stamps.min()) * 1e3
            span_recorder.end(wid, skew_ms=round(skew_ms, 3))
            span_recorder.note_pending_skew("ckpt_skew_ms", skew_ms)
        agreed = multihost_utils.process_allgather(
            np.asarray([round_idx], dtype=np.int64)
        )
        if not (agreed == round_idx).all():
            rounds_seen = agreed.ravel().tolist()
            raise RuntimeError(
                "sharded checkpoint barrier disagreement: processes "
                f"are checkpointing different rounds ({rounds_seen}) — "
                "SPMD round sequencing diverged"
            )
        if is_primary:
            write_manifest(
                config.checkpoint_dir, round_idx,
                {
                    "n_hosts": n_procs,
                    "n_clients": n_clients,
                    "owner_bounds": [int(b) for b in mh_owner_bounds],
                    "cohort": cohort_n,
                    "mesh_devices": int(config.mesh_devices),
                    "shards": [
                        os.path.basename(shard_checkpoint_path(
                            config.checkpoint_dir, round_idx, h, n_procs
                        ))
                        for h in range(n_procs)
                    ],
                },
                span_recorder=span_recorder,
            )
            gc_sharded_checkpoints(
                config.checkpoint_dir, config.checkpoint_keep_last
            )

    def emit_record(round_idx, metrics, fetched_loss, fetched_tel, ctx,
                    tel_rec_fn, phase_round=None, stream_rec=None,
                    audit_fn=None, population_rec=None,
                    multihost_rec=None):
        """Build + persist ONE round's metrics record from already-fetched
        host values: post_round hook, record assembly, quorum/cohort
        telemetry accumulation, client-stats detection, history append +
        metrics.jsonl line. The shared tail of the K=1 ``finalize`` and
        the batched-dispatch ``flush_dispatch`` — one copy, so the record
        layout (and its byte-identical-at-defaults guarantee) cannot
        drift between dispatch shapes. ``tel_rec_fn`` builds the
        telemetry sub-object lazily AFTER post_round (so host-side
        compiles attribute to this round); ``phase_round`` is where
        post_round phase time accumulates (the dispatch's last round
        under batching, so the one telemetry record carries every
        phase)."""
        nonlocal prev_metrics, t_prev_done
        if phase_round is None:
            phase_round = round_idx
        with annotate("post_round"), phase_timer.phase(
                phase_round, "post_round"):
            extra = algorithm.post_round(ctx) or {}
        # Mesh-sharded GTG walk provenance (algorithms/shapley.py): a
        # ``gtg`` dict in the post_round extras is the schema-v10
        # sub-object — routed through the shared record builder below
        # (lowest-version stamping), never inlined into the v1 base.
        gtg_rec = extra.pop("gtg", None)
        now = time.perf_counter()
        # Wall time between successive round completions: covers train +
        # eval + metric fetch + host post_round (Shapley time included —
        # it IS per-round server work). Sums to total wall time (within
        # a batched dispatch the dispatch's wall lands on its first
        # round; later rounds record only their host-side tail).
        record = build_base_round_record(
            config, round_idx, metrics, fetched_loss, fetched_tel, extra,
            round_seconds=now - t_prev_done,
        )
        if "survivor_count" in record:
            telemetry["survivor_counts"].append(record["survivor_count"])
        if record.get("round_rejected"):
            telemetry["rounds_rejected"] += 1
            logger.warning(
                "round %d REJECTED by quorum policy (survivors=%s, "
                "min_survivors=%d): previous global model retained",
                round_idx, record.get("survivor_count"),
                config.min_survivors,
            )
            if span_recorder is not None:
                # Flight-recorder trigger: a quorum rejection is a
                # fault event — snapshot what every subsystem was doing
                # around it into the journal for the postmortem.
                span_recorder.flush_inflight("quorum_rejected")
        t_prev_done = now
        cs_rec = None
        extras = {
            k: float(fetched_tel[k])
            for k in ("quant_mse", "vote_agreement")
            if k in fetched_tel
        }
        if "client_stats" in fetched_tel:
            cs_rec, n_flagged = detect_and_record(
                fetched_tel["client_stats"], client_stats_cfg,
                round_idx, logger=logger,
                participants=fetched_tel.get("participants"),
                extras=extras,
            )
            telemetry["clients_flagged"] += n_flagged
        elif extras:
            # Algorithms without per-client deltas (sign_SGD) report
            # round scalars only; non-finite values become null like
            # every other client-stats field (strict-JSON contract).
            cs_rec = {
                "n_clients": n_clients,
                **{
                    k: (v if np.isfinite(v) else None)
                    for k, v in extras.items()
                },
            }
        async_rec = None
        if "sim_duration" in fetched_tel:
            # Deadline-round outcome (robustness/arrivals.py): the v4
            # ``async`` sub-object. mean_staleness is meaningful only
            # over a non-empty late batch (null keeps strict JSON).
            n_late_rec = int(fetched_tel["late_count"])
            async_rec = {
                "on_time": int(fetched_tel["on_time_count"]),
                "late": n_late_rec,
                "buffer": int(fetched_tel["buffer_count"]),
                "applied": bool(fetched_tel["buffer_applied"]),
                "mean_staleness": (
                    round(float(fetched_tel["mean_staleness"]), 4)
                    if n_late_rec else None
                ),
                "sim_round_s": round(float(fetched_tel["sim_duration"]), 6),
                "sim_round_sync_s": round(
                    float(fetched_tel["sim_duration_sync"]), 6
                ),
                "sim_clock_s": round(float(fetched_tel["sim_clock"]), 6),
            }
            telemetry["sim_async_s"] += float(fetched_tel["sim_duration"])
            telemetry["sim_sync_s"] += float(
                fetched_tel["sim_duration_sync"]
            )
            telemetry["buffer_occupancy"].append(
                int(fetched_tel["buffer_count"])
            )
        val_rec = None
        if vstate is not None and "valuation_scores" in fetched_tel:
            # Streaming valuation fold (telemetry/valuation.py): the
            # round's in-program scores, scaled by the server loss-delta
            # (previous test loss minus this round's — post_round has
            # NOT yet replaced prev_metrics at this point, so the delta
            # is exactly this round's improvement), scatter-folded into
            # the persistent per-client vector. Round 0 (no previous
            # metric) folds a 0 delta — the vector starts moving once
            # there is a baseline to improve on.
            v_ids = fetched_tel.get("participants")
            if v_ids is not None:
                v_ids = np.asarray(v_ids)
            loss_delta = (
                float(prev_metrics["loss"]) - float(metrics["loss"])
                if prev_metrics else 0.0
            )
            vstate.fold(
                v_ids, np.asarray(fetched_tel["valuation_scores"]),
                loss_delta, valuation_cfg.decay,
            )
            audit_rec = audit_fn(v_ids) if audit_fn is not None else None
            if audit_rec is not None:
                telemetry["valuation_last_audit"] = {
                    "round": round_idx, **audit_rec,
                }
                logger.info(
                    "round %d valuation audit: spearman=%s pearson=%s "
                    "(%d permutations, %d subset evals, converged=%s, "
                    "memo_hit_rate=%s, %.1fs)",
                    round_idx, audit_rec["spearman"],
                    audit_rec["pearson"], audit_rec["permutations"],
                    audit_rec["subset_evals"], audit_rec["converged"],
                    audit_rec["memo_hit_rate"], audit_rec["seconds"],
                )
            val_rec = valuation_record(
                vstate, v_ids, loss_delta, audit=audit_rec,
            )
        cm_rec = None
        if cost_ledger is not None and round_idx == config.round - 1:
            # The run's measured per-round wall, averaged over the steady
            # rounds (round 0 carries compile; under batched dispatch a
            # dispatch's wall lands on its first round, so the MEAN over
            # steady rounds — elapsed/rounds — is the honest unit in
            # every dispatch shape).
            walls = [h["round_seconds"] for h in history] + [
                record["round_seconds"]
            ]
            steady = walls[1:] or walls
            cm_rec = costmodel_record(
                cost_ledger,
                trace_rounds=config.cost_model_trace_rounds,
                anchor=config.cost_model_topology,
                measured_ms=1e3 * sum(steady) / len(steady),
                param_bytes=_f32_param_bytes(global_params),
                run_rounds=config.round,
            )
            telemetry["costmodel"] = cm_rec
        pop_rec = None
        if population_rec is not None:
            # The churn-collision flag needs the round's quorum verdict,
            # known only here: rejected AND cohort members departed this
            # round (robustness/population.py, the PR 2 contract's
            # open-world face).
            pop_rec = dict(population_rec)
            pop_rec["rejected_by_churn"] = bool(
                record.get("round_rejected")
                and pop_rec.get("cohort_departs", 0) > 0
            )
            if pop_rec["rejected_by_churn"]:
                telemetry["churn_rejected"] += 1
        tel_rec = tel_rec_fn()
        spans_rec = None
        if span_recorder is not None:
            # Pop the round's span aggregate for the schema-v12
            # sub-object, then drain completed spans to the journal —
            # once per round, the only hot-path journal I/O.
            spans_rec = span_recorder.round_summary(round_idx)
            span_recorder.flush()
        if (
            tel_rec is not None or cs_rec is not None
            or async_rec is not None or stream_rec is not None
            or cm_rec is not None or val_rec is not None
            or pop_rec is not None or gtg_rec is not None
            or multihost_rec is not None or spans_rec is not None
        ):
            record = build_round_record(
                record, tel_rec, cs_rec, async_rec, stream_rec, cm_rec,
                val_rec, population=pop_rec, gtg=gtg_rec,
                multihost=multihost_rec, spans=spans_rec,
            )
        history.append(record)
        if metrics_path:
            with open(metrics_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        logger.info(
            "round %d: test_acc=%.4f test_loss=%.4f (%.2fs)",
            round_idx, metrics["accuracy"], metrics["loss"],
            record["round_seconds"],
        )
        prev_metrics = metrics

    def finalize(p: dict) -> None:
        # Flight-recorder envelope: an EAGER span (open-line journaled
        # before the body runs) covering metric fetch, record emission,
        # and the checkpoint block — the chaos harness's injected crash
        # (maybe_crash, last statement below) fires inside it, so a
        # SIGKILL'd host's journal names this span as its in-flight
        # postmortem without any cleanup code running.
        if span_recorder is None:
            return _finalize(p)
        with span_recorder.span(
            "finalize", "round", round_idx=p["round_idx"], eager=True,
        ):
            return _finalize(p)

    def _finalize(p: dict) -> None:
        tel_keys = [
            k for k in ("survivor_count", "round_rejected", "participants")
            if k in p["aux"]
        ]
        # Client-stats fetch cadence (client_stats_every): the [N, S]
        # matrix and its round scalars ride the round's SINGLE metric
        # device_get below — no extra host sync, async dispatch preserved.
        cs_fetch = (
            client_stats_cfg is not None
            and client_stats_cfg.fetch_round(p["round_idx"])
        )
        cs_keys = [
            k for k in ("client_stats", "quant_mse", "vote_agreement")
            if k in p["aux"]
        ] if cs_fetch else []
        # Valuation scores ride EVERY round's single metric fetch (the
        # host fold needs each round's loss-delta pairing) — N floats,
        # not on the client_stats_every cadence.
        val_keys = (
            ["valuation_scores"]
            if vstate is not None and "valuation_scores" in p["aux"]
            else []
        )
        async_keys = [k for k in _ASYNC_AUX_KEYS if k in p["aux"]]
        with phase_timer.phase(p["round_idx"], "host_sync"), _oom_hint(
                config, p["new_global"], n_clients,
                site="deferred metric fetch"):
            fetched_metrics, fetched_loss, fetched_tel = jax.device_get(
                (p["metrics_dev"], p["mean_loss_dev"],
                 {k: p["aux"][k]
                  for k in tel_keys + cs_keys + val_keys + async_keys})
            )
        metrics = {k: float(v) for k, v in fetched_metrics.items()}
        if p.get("participants_host") is not None and (
            "participants" in fetched_tel
        ):
            # Distributed cohort assembly: the device operand carries the
            # OWNER-permuted cohort (row order = placement order); the
            # record's cohort_hash must stay comparable across
            # topologies, so substitute the host-replayed DRAW-order
            # cohort — same set, canonical order. Safe because the only
            # consumer left under multihost streamed is the hash
            # (client_stats/valuation are cause-named refusals there).
            fetched_tel["participants"] = p["participants_host"]
        ctx = RoundContext(
            round_idx=p["round_idx"],
            global_params=p["new_global"],
            prev_global_params=p["prev_global"],
            sizes=sizes,
            aux=p["aux"],
            metrics=metrics,
            prev_metrics=prev_metrics,
            eval_batches=eval_batches,
            log_dir=log_dir,
        )
        if "client_stats" in fetched_tel:
            # Hand post_round hooks (Shapley's attribution cross-check)
            # the ALREADY-fetched matrix so they never re-transfer the
            # device array the single metric device_get above carried.
            ctx.extra["client_stats_np"] = np.asarray(
                fetched_tel["client_stats"]
            )

        def tel_rec_fn():
            if not phase_timer.enabled:
                return None
            # Attribute post_round/host-side compiles, then fold this
            # round's telemetry into a schema-v2/v3 record (shared
            # builder: utils/reporting.py). Warmup = the first EXECUTED
            # round (it legitimately compiles the round + eval programs);
            # anything later is the shape-instability warning.
            recompile.attribute(p["round_idx"])
            events = recompile.take(p["round_idx"])
            if span_recorder is not None:
                # Recompile events become instant spans: on the stitched
                # timeline a post-warmup compile shows up AT the host
                # and round that paid for it.
                for _fn_name, _secs in events:
                    span_recorder.event(
                        _fn_name, "compile", round_idx=p["round_idx"],
                        seconds=round(_secs, 6),
                    )
            n_compiles = log_round_compiles(
                logger, p["round_idx"], events,
                warmup=p["round_idx"] == start_round,
            )
            if p["round_idx"] > start_round:
                post_warmup_compiles["count"] += n_compiles
            tel_rec = {
                "phase_seconds": {
                    k: round(v, 6)
                    for k, v in sorted(
                        phase_timer.take(p["round_idx"]).items()
                    )
                },
                "compiles": n_compiles,
            }
            if events:
                tel_rec["compiled"] = [name for name, _ in events]
            peak = peak_hbm_bytes()
            if peak is not None:
                tel_rec["peak_hbm_bytes"] = peak
            return tel_rec

        def audit_fn(v_ids):
            """Sparse-cadence GTG cross-validation (telemetry/valuation
            .py): replays THIS round's cohort from its round key against
            the pre-round global params — a pure read, the recorded
            aggregate came from the normal program."""
            if auditor is None or not auditor.due(p["round_idx"]):
                return None
            with annotate("valuation_audit"):
                return auditor.run(
                    p["round_idx"], p["round_key"], p["prev_global"],
                    v_ids, vstate.values,
                    lr_scale=float(
                        lr_factors(config, p["round_idx"], 1)[0]
                    ),
                )

        emit_record(
            p["round_idx"], metrics, fetched_loss, fetched_tel, ctx,
            tel_rec_fn, stream_rec=p.get("stream"), audit_fn=audit_fn,
            population_rec=p.get("population"),
            multihost_rec=p.get("multihost"),
        )

        if (
            checkpointing
            and (p["round_idx"] + 1) % config.checkpoint_every == 0
        ):
            algo_state = _algo_checkpoint_state(
                algorithm, metrics, p["server_state"],
                p.get("async_state"),
                vstate.values if vstate is not None else None,
                # Population events for this round were applied
                # before finalize (pipelining is off under dynamic),
                # so the snapshot is exactly the state the NEXT
                # round draws from.
                pop.checkpoint_state(store) if pop is not None
                else None,
            )
            if mh:
                _save_sharded_checkpoint(
                    p["round_idx"], p["new_global"], p["client_state"],
                    algo_state, p["key"],
                )
            else:
                save_checkpoint(
                    os.path.join(
                        config.checkpoint_dir,
                        f"round_{p['round_idx']}.ckpt"
                    ),
                    p["round_idx"], p["new_global"], p["client_state"],
                    algo_state,
                    p["key"],
                )
                gc_checkpoints(config.checkpoint_dir,
                               config.checkpoint_keep_last)
        # Chaos-harness hook (robustness/chaos.py): inert unless
        # DLS_CRASH_AT_ROUND is set. Placed after the checkpoint block so
        # an injected crash models "the process died right after round N
        # was persisted".
        maybe_crash(p["round_idx"])

    # Dispatch sizes already compiled this run (rounds_per_dispatch > 1):
    # a size seen for the first time (remainder/checkpoint-clipped
    # dispatches) legitimately compiles its own scan program — logged as
    # warmup, not as the shape-instability warning.
    seen_dispatch_sizes: set[int] = set()

    def flush_dispatch(d: dict) -> None:
        """Record a whole batched dispatch (rounds_per_dispatch > 1): ONE
        device_get for the stacked per-round metrics/telemetry, then one
        emit_record per round. Phase timings and recompile attribution
        are per-DISPATCH, attached to the dispatch's LAST round's record
        (the only one whose post_round has already run when its record is
        written; docs/OBSERVABILITY.md)."""
        first, k = d["round_start"], d["k"]
        last = first + k - 1
        rounds = range(first, last + 1)
        aux_k = d["aux"]
        tel_keys = [
            name for name in
            ("survivor_count", "round_rejected", "participants")
            if name in aux_k
        ]
        # Client-stats cadence at batch granularity: the stacked rows ride
        # the dispatch's single device_get; records carry them only for
        # rounds on the client_stats_every cadence (matching K=1).
        fetch_rounds = {
            r for r in rounds
            if client_stats_cfg is not None
            and client_stats_cfg.fetch_round(r)
        }
        cs_keys = [
            name for name in ("client_stats", "quant_mse", "vote_agreement")
            if name in aux_k
        ] if fetch_rounds else []
        # Valuation scores: stacked [K, N] — every round's row feeds its
        # own loss-delta fold (no cadence; the vector must not skip
        # rounds).
        val_keys = (
            ["valuation_scores"]
            if vstate is not None and "valuation_scores" in aux_k
            else []
        )
        async_keys = [name for name in _ASYNC_AUX_KEYS if name in aux_k]
        with phase_timer.phase(last, "host_sync"), _oom_hint(
                config, d["new_global"], n_clients,
                site="deferred metric fetch"):
            fetched_metrics, fetched_loss, fetched_tel = jax.device_get(
                (d["metrics"], d["mean_loss"],
                 {name: aux_k[name]
                  for name in tel_keys + cs_keys + val_keys + async_keys})
            )

        def tel_rec_fn():
            if not phase_timer.enabled:
                return None
            recompile.attribute(last)
            events = recompile.take(last)
            warm = first == start_round or k not in seen_dispatch_sizes
            seen_dispatch_sizes.add(k)
            n_compiles = log_round_compiles(logger, last, events, warmup=warm)
            if not warm:
                post_warmup_compiles["count"] += n_compiles
            tel_rec = {
                "phase_seconds": {
                    name: round(v, 6)
                    for name, v in sorted(phase_timer.take(last).items())
                },
                "compiles": n_compiles,
                # Tells consumers (scripts/report_run.py) the phase times
                # and compile counts cover this many rounds — render
                # per-dispatch, never double-count.
                "dispatch_rounds": k,
            }
            if warm and n_compiles:
                # First dispatch of this length: its compiles are
                # expected, so offline reporting must not count them as
                # post-warmup shape instability.
                tel_rec["warmup"] = True
            if events:
                tel_rec["compiled"] = [name for name, _ in events]
            peak = peak_hbm_bytes()
            if peak is not None:
                tel_rec["peak_hbm_bytes"] = peak
            return tel_rec

        for i, round_idx in enumerate(rounds):
            metrics = {
                name: float(v[i]) for name, v in fetched_metrics.items()
            }
            row_keys = tel_keys + async_keys + val_keys + (
                cs_keys if round_idx in fetch_rounds else []
            )
            tel_row = {name: fetched_tel[name][i] for name in row_keys}
            ctx = RoundContext(
                round_idx=round_idx,
                # Dispatch-granular params — the supports_round_batching
                # contract: post_round sees the dispatch-FINAL model and
                # the dispatch-initial previous one.
                global_params=d["new_global"],
                prev_global_params=d["prev_global"],
                sizes=sizes,
                aux=_StackedAuxRow(aux_k, i),
                metrics=metrics,
                prev_metrics=prev_metrics,
                eval_batches=eval_batches,
                log_dir=log_dir,
            )
            if "client_stats" in tel_row:
                ctx.extra["client_stats_np"] = np.asarray(
                    tel_row["client_stats"]
                )
            emit_record(
                round_idx, metrics, fetched_loss[i], tel_row, ctx,
                tel_rec_fn if round_idx == last else (lambda: None),
                phase_round=last,
                # Per-DISPATCH transfer stats, on the dispatch's last
                # record like the phase timings (docs/OBSERVABILITY.md).
                stream_rec=d.get("stream") if round_idx == last else None,
            )
        # Dispatch sizes are clipped to checkpoint boundaries, so the
        # cadence only ever fires on the dispatch's last round — where
        # the carried client/server/RNG state is exactly that round's.
        if checkpointing and (last + 1) % config.checkpoint_every == 0:
            save_checkpoint(
                os.path.join(config.checkpoint_dir, f"round_{last}.ckpt"),
                last, d["new_global"], d["client_state"],
                _algo_checkpoint_state(
                    algorithm, prev_metrics, d["server_state"],
                    d.get("async_state"),
                    vstate.values if vstate is not None else None,
                ),
                d["key"],
            )
            gc_checkpoints(config.checkpoint_dir, config.checkpoint_keep_last)
        maybe_crash(last)

    profile_from = getattr(config, "profile_from_round", 0)
    # SIGTERM grace hook (TPU preemption notice, docs/ROBUSTNESS.md): the
    # handler only sets a flag; the round loop finishes the in-flight
    # round, flushes any deferred round, writes a final checkpoint, and
    # returns cleanly. Installed only in the main thread (signal.signal
    # raises elsewhere — e.g. the threaded test harness), and the previous
    # handler is restored on exit so library callers keep their own.
    preempt = {"flag": False}
    prev_sigterm = None
    sigterm_installed = False
    if threading.current_thread() is threading.main_thread():
        def _on_sigterm(signum, frame):
            preempt["flag"] = True

        try:
            prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
            sigterm_installed = True
        except ValueError:
            pass
    completed_round = start_round - 1
    preempted_at = None
    with ExitStack() as profile_stack:
        if recompile is not None:
            # Scoped to the round loop: the monitor owns process-global
            # logging state (jax_log_compiles + compile-logger capture),
            # restored on exit even if a round raises.
            profile_stack.enter_context(recompile)
        if config.profile_dir and profile_from <= start_round:
            profile_stack.enter_context(profile_session(config.profile_dir))
            profile_from = None  # entered
        # try/finally: if a later round crashes (OOM, preemption, SIGINT),
        # the deferred round that already completed on device still gets its
        # metrics line and checkpoint written before unwinding.
        try:
            if batched:
                # Batched dispatches (rounds_per_dispatch > 1): the host
                # loop walks batch boundaries instead of rounds. Dispatch
                # size = min(K, rounds remaining, distance to the next
                # checkpoint boundary), so checkpoint_every and SIGTERM
                # finish-in-flight semantics keep working at batch
                # granularity; each distinct size compiles its own scan
                # program once (cached below — a remainder dispatch is a
                # different program, counted as warmup, not instability).
                batched_jits: dict[int, object] = {}
                lr_active = config.lr_schedule.lower() != "constant"
                round_idx = start_round

                def _dispatch_len(start: int) -> int:
                    """Dispatch size from ``start``: min(K, rounds
                    remaining, distance to the next checkpoint boundary).
                    Clipped from the CONFIG, not `checkpointing` (which
                    is primary-gated): under multihost SPMD every
                    process must choose the same dispatch length or they
                    run different scan programs and the collectives
                    desync. Only the checkpoint WRITE is primary-only."""
                    k = min(K, config.round - start)
                    if config.checkpoint_dir and config.checkpoint_every:
                        k = min(
                            k,
                            config.checkpoint_every
                            - (start % config.checkpoint_every),
                        )
                    return k

                def _stream_plan(from_key, k):
                    """Host replay of the batched scan's key chain
                    (make_streamed_batched_round_fn does the same k
                    ``key, round_key = split(key)`` steps): the k
                    cohorts this dispatch trains, plus the key cursor
                    AFTER it — which is what lets the next dispatch's
                    cohorts prefetch before this one returns."""
                    hk = from_key
                    idx_list = []
                    for _ in range(k):
                        hk, rk = jax.random.split(hk)
                        idx_list.append(streamer.cohort_for(rk))
                    return idx_list, hk

                # (dispatch start round, its cohort plan, key cursor
                # after it) — prefetched while the previous dispatch ran.
                stream_next = None
                while round_idx < config.round:
                    k = _dispatch_len(round_idx)
                    last_idx = round_idx + k - 1
                    if (
                        config.profile_dir
                        and profile_from is not None
                        and round_idx >= profile_from
                    ):
                        # Deferred trace start at dispatch granularity
                        # (rationale: the K=1 loop below).
                        profile_stack.enter_context(
                            profile_session(config.profile_dir)
                        )
                        profile_from = None
                    dispatch = batched_jits.get(k)
                    if dispatch is None:
                        if stream_sampled:
                            # Streamed scan: the k cohorts' slices arrive
                            # stacked [k, cohort, ...]; server_state is
                            # operand 1 (there is no client-state carry —
                            # refused above when state exists).
                            dispatch = jax.jit(
                                make_streamed_batched_round_fn(
                                    round_fn, server_update_fn, eval_fn,
                                    k, lr_active,
                                    async_mode=async_ctl is not None,
                                ),
                                donate_argnums=(1,),
                            )
                        else:
                            dispatch = jax.jit(
                                make_batched_round_fn(
                                    round_fn, server_update_fn, eval_fn, k,
                                    lr_active,
                                    async_mode=async_ctl is not None,
                                ),
                                donate_argnums=(1, 2),
                            )
                        batched_jits[k] = dispatch
                    # The schedule factors become a length-k f32 operand
                    # vector (lr_factors — same values, same cast as the
                    # K=1 scalar operand); the constant default is
                    # omitted so it constant-folds exactly like the
                    # unbatched program.
                    lr_args = (
                        (jnp.asarray(lr_factors(config, round_idx, k)),)
                        if lr_active else ()
                    )
                    prev_global = global_params
                    async_kw = (
                        {"async_state": async_state}
                        if async_ctl is not None else {}
                    )
                    stream_rec = None
                    with annotate(
                        f"fl_rounds_{round_idx}_{last_idx}"
                    ), _oom_hint(config, global_params, n_clients):
                        if stream_sampled:
                            if (
                                stream_next is not None
                                and stream_next[0] == round_idx
                            ):
                                idx_list, hk_after = stream_next[1:]
                            else:
                                # First dispatch / resume: the k draws
                                # get their own `sample` phase window.
                                with phase_timer.phase(
                                        last_idx, "sample"):
                                    idx_list, hk_after = _stream_plan(
                                        key, k
                                    )
                            (sx, sy, sm, ssz, sidx), stream_rec = (
                                streamer.acquire(idx_list, stack=True)
                            )
                            if k > 1:
                                stream_rec["dispatch_rounds"] = k
                            with phase_timer.phase(
                                    last_idx, "client_step") as _ph:
                                out = dispatch(
                                    global_params, server_state, key,
                                    sx, sy, sm, ssz, sidx, eval_batches,
                                    *lr_args, **async_kw,
                                )
                                if async_ctl is not None:
                                    (
                                        global_params, server_state, key,
                                        metrics_k, aux_k, async_state,
                                    ) = out
                                else:
                                    (
                                        global_params, server_state, key,
                                        metrics_k, aux_k,
                                    ) = out
                                # Prefetch the NEXT dispatch's cohorts
                                # while this dispatch computes — BEFORE
                                # the fence/flush syncs on its results.
                                nxt = last_idx + 1
                                stream_next = None
                                if nxt < config.round and not preempt["flag"]:
                                    k2 = _dispatch_len(nxt)
                                    # The k2 draws overlap this
                                    # dispatch's compute; carve their
                                    # host cost out of client_step into
                                    # the `sample` phase (K=1 rationale
                                    # above).
                                    _t_s = clock.monotonic()
                                    idx2, hk2 = _stream_plan(hk_after, k2)
                                    phase_timer.carve(
                                        last_idx, "sample",
                                        clock.monotonic() - _t_s,
                                        "client_step",
                                    )
                                    stream_next = (nxt, idx2, hk2)
                                    streamer.prefetch(idx2, stack=True)
                                _ph.fence((global_params, metrics_k))
                        else:
                            if (
                                stream_full
                                and startup_stream["rec"] is not None
                            ):
                                # The one-shot population upload lands on
                                # the first dispatch's record.
                                stream_rec = startup_stream["rec"]
                                startup_stream["rec"] = None
                                if k > 1:
                                    stream_rec["dispatch_rounds"] = k
                            with phase_timer.phase(
                                    last_idx, "client_step") as _ph:
                                out = dispatch(
                                    global_params, client_state,
                                    server_state, key, cx, cy, cmask,
                                    sizes, eval_batches,
                                    *lr_args, **async_kw,
                                )
                                if async_ctl is not None:
                                    (
                                        global_params, client_state,
                                        server_state, key, metrics_k,
                                        aux_k, async_state,
                                    ) = out
                                else:
                                    (
                                        global_params, client_state,
                                        server_state, key, metrics_k,
                                        aux_k,
                                    ) = out
                                _ph.fence((global_params, metrics_k))
                    if recompile is not None:
                        recompile.attribute(last_idx)
                    mean_loss_k = aux_k.get("mean_client_loss")
                    if mean_loss_k is None:
                        mean_loss_k = np.full(k, np.nan)
                    flush_dispatch({
                        "round_start": round_idx,
                        "k": k,
                        "metrics": metrics_k,
                        "mean_loss": mean_loss_k,
                        "aux": aux_k,
                        "new_global": global_params,
                        "prev_global": prev_global,
                        "client_state": client_state,
                        "server_state": server_state,
                        "async_state": async_state,
                        "key": key,
                        "stream": stream_rec,
                    })
                    completed_round = last_idx
                    round_idx = last_idx + 1
                    if preempt["flag"]:
                        # Finish-in-flight at batch granularity: the
                        # dispatched rounds completed and were recorded;
                        # no new dispatch is launched.
                        break
            else:
                # Next round's host-replayed cohort (stream_sampled): the
                # prefetched upload this index list describes is already
                # in flight when the round that uses it starts.
                stream_next_idx = None
                for round_idx in range(start_round, config.round):
                    if (
                        config.profile_dir
                        and profile_from is not None
                        and round_idx >= profile_from
                    ):
                        # Deferred trace start (config.profile_from_round):
                        # round 0's XLA compile floods the tunnel profiler's
                        # event buffer and device events get dropped —
                        # measured: whole-loop flagship traces come back
                        # empty or truncated at a run-varying point, while a
                        # steady-state round traced after compile captures
                        # fully (scripts/profile_sign_round.py's method).
                        profile_stack.enter_context(
                            profile_session(config.profile_dir)
                        )
                        profile_from = None
                    key, round_key = jax.random.split(key)
                    if span_recorder is not None and streamer is not None:
                        # Skew/occupancy spans emitted inside the
                        # streamer (spill exchange, prefetch worker)
                        # attribute to the round being dispatched.
                        streamer.span_round = round_idx
                    with annotate(f"fl_round_{round_idx}"), _oom_hint(
                        config, global_params, n_clients
                    ):
                        # The schedule factor is a traced operand only when a
                        # schedule is active; the constant default uses the
                        # round_fn's Python default 1.0, which constant-folds
                        # at trace time (no per-step scale multiply in the
                        # compiled program). lr_factors is the one
                        # formula shared with the batched dispatch's
                        # operand vector.
                        lr_args = () if config.lr_schedule.lower() == (
                            "constant"
                        ) else (
                            jnp.float32(lr_factors(config, round_idx, 1)[0]),
                        )
                        async_kw = (
                            {"async_state": async_state}
                            if async_ctl is not None else {}
                        )
                        stream_rec = None
                        pop_rec = None
                        mh_rec = None
                        mh_plan = None
                        if stream_sampled:
                            # Streamed dispatch: cohort slices arrive as
                            # pre-gathered operands (prefetched while the
                            # previous round computed); persistent state
                            # gathers from the host store (post the
                            # previous round's writeback) and scatters
                            # back after this dispatch.
                            pop_events = pop_words = dep_mask = None
                            if pop is not None:
                                # Dynamic population: the cohort is
                                # drawn from the PRE-event registered
                                # index space (departed masked out of
                                # the hashed stream); this round's
                                # events come from the fold_in-decoupled
                                # registration stream and APPLY after
                                # the dispatch — a joiner is sampleable
                                # from the next round, a departure that
                                # hits this cohort rides the departed
                                # operand. Drift levels advance before
                                # the gather so sampled drifting
                                # clients train on this round's labels.
                                pop_words = pop_key_words(
                                    round_key, pop.seed
                                )
                                with phase_timer.phase(
                                        round_idx, "sample"):
                                    idx_np = streamer.cohort_for(
                                        round_key,
                                        n=pop.n_registered,
                                        alive=pop.alive,
                                        k=cohort_n,
                                    )
                                pop_events = pop.draw_events(
                                    pop_words, round_idx
                                )
                                dep_mask = pop.cohort_departed_mask(
                                    pop_events, idx_np
                                )
                                pop.apply_drift(store, round_idx, idx_np)
                            elif stream_next_idx is not None:
                                idx_np = stream_next_idx
                            else:
                                # First round / resume: the draw is not
                                # hidden behind a prior dispatch — its
                                # own `sample` phase window (under the
                                # distributed store this window also
                                # covers the owner assembly + spill
                                # exchange).
                                with phase_timer.phase(
                                        round_idx, "sample"):
                                    idx_np = streamer.cohort_for(
                                        round_key
                                    )
                                    if mh:
                                        idx_np = streamer.plan(idx_np)
                            stream_next_idx = None
                            if mh:
                                # Owner-sharded assembly: this host's
                                # block rows, with ownership-imbalance
                                # spill already exchanged at plan time;
                                # the upload adds the draw_pos operand
                                # that maps rows back to draw order.
                                mh_plan = idx_np
                                (
                                    (sx, sy, sm, ssz, sidx, sdpos),
                                    stream_rec, mh_plan,
                                ) = streamer.acquire_plan(mh_plan)
                                mh_kw = {"draw_pos": sdpos}
                            else:
                                (sx, sy, sm, ssz, sidx), stream_rec = (
                                    streamer.acquire([idx_np])
                                )
                                mh_kw = {}
                            state_k = None
                            if store.state is not None:
                                if mh:
                                    # Owner-assembled block state (own
                                    # rows local, spill rows exchanged),
                                    # placed straight into the
                                    # client-axis layout.
                                    state_k = streamer.gather_state_device(
                                        mh_plan
                                    )
                                else:
                                    # Donated operand: owned buffers,
                                    # not a zero-copy view of the numpy
                                    # gather.
                                    state_k = _owned_device_tree(
                                        algorithm.gather_client_state(
                                            store, idx_np
                                        )
                                    )
                                    if mesh is not None:
                                        # Cohort state joins the cohort
                                        # slice's client-axis layout.
                                        state_k = shard_client_data(
                                            state_k, mesh
                                        )
                            dyn_kw = (
                                {"departed": jnp.asarray(dep_mask)}
                                if pop is not None else {}
                            )
                            with phase_timer.phase(
                                    round_idx, "client_step") as _ph:
                                new_global, new_state_k, aux = round_jit(
                                    global_params, state_k, sx, sy, sm,
                                    ssz, sidx, round_key,
                                    *lr_args, **async_kw, **dyn_kw,
                                    **mh_kw,
                                )
                                # Prefetch the next round's cohort while
                                # this dispatch computes (the upload runs
                                # on the streamer's worker thread). The
                                # draw deliberately overlaps device
                                # compute; its host cost is carved out
                                # of this client_step window into the
                                # `sample` phase so the ~1 s exact
                                # replay at N=1e6 stays visible.
                                # Dynamic populations draw synchronously
                                # instead: the next cohort depends on
                                # this round's registration events
                                # (applied below), and the O(cohort)
                                # hashed draw is microseconds.
                                if pop is None and (
                                    round_idx + 1 < config.round
                                ) and not preempt["flag"]:
                                    _, _nxt_rk = jax.random.split(key)
                                    if mh:
                                        # Plan (incl. the collective
                                        # spill exchange) on the MAIN
                                        # thread at the same loop point
                                        # on every host — collective
                                        # launch order stays identical
                                        # across processes; only the
                                        # device_put assembly rides the
                                        # worker thread.
                                        _t_s = clock.monotonic()
                                        stream_next_idx = streamer.plan(
                                            streamer.cohort_for(_nxt_rk)
                                        )
                                        phase_timer.carve(
                                            round_idx, "sample",
                                            clock.monotonic() - _t_s,
                                            "client_step",
                                        )
                                        streamer.prefetch_plan(
                                            stream_next_idx
                                        )
                                    else:
                                        stream_next_idx = (
                                            streamer.cohort_for(_nxt_rk)
                                        )
                                        phase_timer.carve(
                                            round_idx, "sample",
                                            streamer.last_sample_seconds,
                                            "client_step",
                                        )
                                        streamer.prefetch(
                                            [stream_next_idx]
                                        )
                                _ph.fence((new_global, aux))
                            # Host store is the source of truth between
                            # dispatches: checkpoint/resume read it.
                            streamer.writeback(
                                mh_plan if mh else idx_np, new_state_k,
                                stream_rec,
                            )
                            if mh:
                                mh_rec = streamer.multihost_record(
                                    mh_plan, stream_rec or {}
                                )
                            if pop is not None:
                                # Registration events apply at the round
                                # boundary, after the writeback and
                                # before this round's checkpoint: the
                                # persisted state is exactly what the
                                # next round's draw sees.
                                pop.apply(
                                    pop_events, store,
                                    state_proto=pop_state_proto,
                                    words=pop_words,
                                )
                                pop_rec = pop.round_record(
                                    pop_events,
                                    int(np.count_nonzero(dep_mask)),
                                )
                        else:
                            if (
                                stream_full
                                and startup_stream["rec"] is not None
                            ):
                                # One-shot population upload: recorded on
                                # the first round's record.
                                stream_rec = startup_stream["rec"]
                                startup_stream["rec"] = None
                            if mh:
                                # Full-cohort distributed upload: shard
                                # provenance on every round's record
                                # (spill is structurally zero — owner
                                # bounds ARE the device blocks).
                                mh_rec = streamer.multihost_record(
                                    None, stream_rec or {}
                                )
                            with phase_timer.phase(
                                    round_idx, "client_step") as _ph:
                                new_global, client_state, aux = round_jit(
                                    global_params, client_state, cx, cy,
                                    cmask, sizes,
                                    round_key, *lr_args, **async_kw,
                                )
                                _ph.fence((new_global, aux))
                        if async_ctl is not None:
                            # Pop the buffer carry before any record/aux
                            # consumer sees it; it becomes the next
                            # round's async_state operand.
                            aux = dict(aux)
                            async_state = aux.pop("async_state")
                        if server_update_jit is not None:
                            # When the round program carries a quorum verdict,
                            # the server optimizer must see it: a rejected
                            # round freezes the optimizer state and leaves the
                            # params untouched (momentum alone would otherwise
                            # move the "retained" model).
                            srv_args = (global_params, new_global, server_state)
                            if "round_rejected" in aux:
                                srv_args += (aux["round_rejected"],)
                            with phase_timer.phase(
                                    round_idx, "aggregate") as _ph:
                                new_global, server_state = server_update_jit(
                                    *srv_args
                                )
                                _ph.fence(new_global)
                    with annotate("server_eval"), _oom_hint(
                        config, global_params, n_clients, site="eval"
                    ):
                        with phase_timer.phase(round_idx, "eval") as _ph:
                            metrics_dev = evaluate(new_global, *eval_batches)
                            _ph.fence(metrics_dev)
                    if recompile is not None:
                        # Compiles are synchronous with trace/lower, so events
                        # pending here came from this round's dispatches
                        # (under pipelining, the deferred finalize of round
                        # r-1 runs after this and must not absorb them).
                        recompile.attribute(round_idx)
                    entry = {
                        "round_idx": round_idx,
                        "round_key": round_key,
                        "new_global": new_global,
                        "prev_global": global_params,
                        # Sampled streamed: the (post-writeback) host
                        # store is what a checkpoint must persist.
                        "client_state": (
                            store.state if stream_sampled
                            else None if pipelined else client_state
                        ),
                        "aux": aux,
                        "metrics_dev": metrics_dev,
                        "mean_loss_dev": aux.get("mean_client_loss", np.nan),
                        "key": key,
                        "server_state": server_state,
                        "async_state": async_state,
                        "stream": stream_rec,
                        "population": pop_rec,
                        "multihost": mh_rec,
                        # Draw-order cohort for the record's cohort_hash
                        # (the device operand is owner-permuted under
                        # the distributed layout).
                        "participants_host": (
                            mh_plan.idx if mh_plan is not None else None
                        ),
                    }
                    global_params = new_global
                    if pipelined:
                        # Take ownership of `entry` before finalizing the prior
                        # round: if that finalize raises, the finally block still
                        # records this round (the raising round is what's lost).
                        prev_pending, pending = pending, entry
                        if prev_pending is not None:
                            finalize(prev_pending)
                    else:
                        finalize(entry)
                    completed_round = round_idx
                    if preempt["flag"]:
                        # Finish-in-flight semantics: this round completed (and
                        # with pipelining its deferred finalize runs in the
                        # crash-flush below); no new round is dispatched.
                        break
        except BaseException as crash_exc:
            # Flight recorder (telemetry/spans.py): an unhandled crash
            # force-flushes the last-K spans plus every still-open span
            # with its `inflight` marker — the journal then names
            # exactly what this host was doing when the run died (a
            # peer's SIGKILL surfacing as a broken collective lands
            # here too). Best-effort by construction: flush_inflight
            # never raises past its own I/O, and the original exception
            # always propagates.
            if span_recorder is not None:
                try:
                    span_recorder.flush_inflight(
                        type(crash_exc).__name__
                    )
                except Exception:
                    pass
            raise
        finally:
            if sigterm_installed:
                signal.signal(signal.SIGTERM, prev_sigterm)
            if streamer is not None:
                # Join the worker thread (an in-flight prefetch must not
                # outlive the run) — the store keeps its state for the
                # checkpoint/result paths below.
                streamer.close()
            if pending is not None:
                # Crash-flush of the last deferred round. Best-effort: if
                # finalize itself is what failed in-loop (full disk, post_round
                # bug), don't let a second failure here supersede the original
                # exception in the propagated traceback.
                try:
                    finalize(pending)
                except Exception:
                    logger.exception(
                        "failed to record round %d during unwind",
                        pending["round_idx"],
                    )
                finally:
                    pending = None

    if preempt["flag"]:
        # Graceful preemption: the in-flight round finished and was
        # finalized above; persist it even off the checkpoint_every
        # cadence so the resumed run loses nothing, then exit cleanly.
        preempted_at = completed_round
        if span_recorder is not None:
            # Flight recorder: journal the preemption moment (last-K
            # spans + anything still open) so a postmortem can see what
            # the SIGTERM interrupted even though the exit is clean.
            span_recorder.flush_inflight("sigterm")
        if mh and config.checkpoint_dir:
            # No off-cadence force-write under the distributed store:
            # the sharded commit needs a cross-host barrier, and SIGTERM
            # delivery is per-process — a host whose peer never got the
            # signal would block in the barrier instead of exiting. The
            # checkpoint_every cadence (whose barrier every host
            # reaches by SPMD construction) is the durability contract.
            logger.warning(
                "preempted at round %d (SIGTERM): sharded checkpoints "
                "persist on the checkpoint_every cadence only (last "
                "committed manifest is the resume point); exiting "
                "cleanly", completed_round,
            )
        elif (
            config.checkpoint_dir and is_primary
            and completed_round >= start_round
        ):
            forced_path = os.path.join(
                config.checkpoint_dir, f"round_{completed_round}.ckpt"
            )
            if not os.path.exists(forced_path):
                save_checkpoint(
                    forced_path, completed_round, global_params,
                    store.state if stream_sampled else client_state,
                    _algo_checkpoint_state(
                        algorithm, prev_metrics, server_state, async_state,
                        vstate.values if vstate is not None else None,
                        pop.checkpoint_state(store) if pop is not None
                        else None,
                    ),
                    key,
                )
                gc_checkpoints(
                    config.checkpoint_dir, config.checkpoint_keep_last
                )
            logger.warning(
                "preempted at round %d (SIGTERM): final checkpoint %s "
                "written; exiting cleanly — resume with config.resume=True",
                completed_round, forced_path,
            )
        else:
            logger.warning(
                "preempted at round %d (SIGTERM): no checkpoint_dir "
                "configured, exiting cleanly without persisting",
                completed_round,
            )

    span_summary = None
    if span_recorder is not None:
        # Final journal drain + close; the run summary is what bench.py's
        # mhost leg and scripts read (run-total counts, seconds by
        # category, and the worst barrier skews seen).
        span_summary = span_recorder.run_summary()
        span_recorder.close()

    total = time.perf_counter() - t_start
    # len(history) counts THIS run's finalized rounds (a preempted run
    # completes fewer than config.round - start_round).
    n_rounds = len(history)
    logger.info(
        "finished %d rounds x %d clients in %.2fs (%.1f client-rounds/sec)",
        n_rounds, n_clients, total,
        n_rounds * n_clients / max(total, 1e-9),
    )
    return {
        "global_params": global_params,
        "client_state": store.state if stream_sampled else client_state,
        "history": history,
        "algorithm": algorithm,
        "final_accuracy": history[-1]["test_accuracy"] if history else None,
        "total_seconds": total,
        "client_rounds_per_sec": n_rounds * n_clients / max(total, 1e-9),
        "client_chunk_size": config.client_chunk_size,
        "mesh": mesh,
        # Robustness telemetry (quorum policy, docs/ROBUSTNESS.md): always
        # present so downstream consumers (bench.py) need no key checks.
        "rounds_rejected": telemetry["rounds_rejected"],
        # Run telemetry (docs/OBSERVABILITY.md): post-warmup XLA compile
        # count — 0 on a shape-stable run; None when telemetry is off.
        "telemetry_level": tel_level,
        "post_warmup_compiles": (
            post_warmup_compiles["count"]
            if post_warmup_compiles is not None else None
        ),
        "mean_survivor_count": (
            float(np.mean(telemetry["survivor_counts"]))
            if telemetry["survivor_counts"] else None
        ),
        # Client statistics (telemetry/client_stats.py): total clients
        # flagged by the per-round anomaly detector over the run — 0 on a
        # clean run; None when client_stats is off.
        "clients_flagged": (
            telemetry["clients_flagged"]
            if client_stats_cfg is not None else None
        ),
        # Async federation (robustness/arrivals.py): simulated-clock
        # speedup of deadline rounds over the wait-for-everyone sync
        # counterfactual, the final simulated clock, and the mean
        # staleness-buffer occupancy — all None when async_mode='off'.
        # The speedup ratio covers the rounds THIS process executed (a
        # per-run measurement, like round_seconds); the clock is read
        # from the carried buffer state, so a resumed run reports the
        # CUMULATIVE simulated time — consistent with the sim_clock_s
        # the records carry.
        "async_speedup_ratio": (
            telemetry["sim_sync_s"] / telemetry["sim_async_s"]
            if async_ctl is not None and telemetry["sim_async_s"] > 0
            else None
        ),
        "sim_clock_seconds": (
            float(jax.device_get(async_state["clock"]))
            if async_ctl is not None else None
        ),
        "mean_buffer_occupancy": (
            float(np.mean(telemetry["buffer_occupancy"]))
            if telemetry["buffer_occupancy"] else None
        ),
        # Streamed residency (parallel/streaming.py): run-total transfer
        # accounting and the fraction of host->HBM upload time the
        # double-buffered prefetch hid behind compute — the number
        # bench.py's `stream` leg records and compare_bench.py gates
        # (--stream-overlap-threshold). All None when resident.
        "client_residency": config.client_residency,
        "stream_overlap_ratio": (
            streamer.overlap_ratio() if streamer is not None else None
        ),
        "stream_h2d_bytes": (
            streamer.totals["h2d_bytes"] if streamer is not None else None
        ),
        "stream_d2h_bytes": (
            streamer.totals["d2h_bytes"] if streamer is not None else None
        ),
        # Cohort-draw replay cost (ops/sampling.py samplers): run-total
        # host seconds spent re-deriving cohorts from the round-key
        # chain — the `sample` phase's run total, the number the
        # participation_sampler knob exists to shrink. None when
        # resident (no host replay happens).
        "participation_sampler": config.participation_sampler,
        "stream_sample_seconds": (
            streamer.totals["sample_seconds"]
            if streamer is not None else None
        ),
        # Distributed shard store (streamed x multihost;
        # parallel/streaming.DistributedCohortStreamer): this host's
        # ownership summary and the run-total assembly traffic — spill
        # rows (the per-round ownership imbalance) and the bytes they
        # moved over DCN. None on single-process runs, the off-gate
        # convention.
        "stream_dcn_bytes": (
            streamer.totals.get("dcn_bytes") if mh else None
        ),
        "multihost_summary": (
            {
                "hosts": n_procs,
                "host_id": jax.process_index(),
                "owned_clients": store.n_owned,
                "shard_bytes": int(
                    store.data_bytes()
                    + (store.state_bytes()
                       if store.state is not None else 0)
                ),
                "spill_rows": int(streamer.totals.get("spill_rows", 0)),
                "dcn_bytes": int(streamer.totals.get("dcn_bytes", 0)),
            }
            if mh else None
        ),
        # Predictive cost model (telemetry/costmodel.py): the schema-v6
        # costmodel sub-object the run's last record carried — None when
        # cost_model_trace is unset, the trace was empty, or the run was
        # preempted before its last round.
        "costmodel": telemetry["costmodel"],
        # Always-on client valuation (telemetry/valuation.py): the
        # top/bottom client tables + the latest audit (bench.py's
        # ``valuation`` leg reads these); ``valuation_state`` is the
        # live ValuationState for library callers/scripts that need the
        # full vector (like ``algorithm``, an object — not JSON). Both
        # None when client_valuation='off'.
        "client_valuation": config.client_valuation,
        "valuation": (
            vstate.summary(telemetry["valuation_last_audit"])
            if vstate is not None else None
        ),
        "valuation_state": vstate,
        # GTG cross-round memo reuse (config.gtg_cross_round_memo,
        # ROADMAP item 4b): the last walk's cross-round subset-utility
        # hit rate — None when the memo is off or no walk ran.
        "gtg_memo_hit_rate": getattr(
            algorithm, "gtg_memo_hit_rate", None
        ),
        # Open-world population (robustness/population.py): the
        # registration stream's run summary — growth ratio, alive count,
        # total joins/departs, and how many quorum rejections coincided
        # with in-cohort departures (bench.py's churn leg reads these).
        # "static" mode reports None, the off-gate convention.
        "population": config.population,
        "population_summary": (
            pop.summary(telemetry["churn_rejected"])
            if pop is not None else None
        ),
        # Distributed tracing (telemetry/spans.py): this host's span
        # journal path + run-total span counts and worst barrier skews —
        # None when span_trace='off', the off-gate convention.
        "span_trace": config.span_trace,
        "span_summary": span_summary,
        "preempted_at": preempted_at,
    }


def run_sweep(config_or_spec, dataset=None, client_data=None):
    """Multi-experiment front door (sweep/): run a fleet of experiments
    — vmapped over an experiment axis where the points allow, scheduled
    through config-hash-grouped warm programs where they don't. Thin
    re-export so ``simulator`` stays the one entry module; the engine
    lives in sweep/engine.py (imported lazily — solo runs never pay the
    import)."""
    from distributed_learning_simulator_tpu.sweep import (
        run_sweep as _run_sweep,
    )

    return _run_sweep(config_or_spec, dataset=dataset,
                      client_data=client_data)


def main(argv: list[str] | None = None):
    from distributed_learning_simulator_tpu.config import get_config
    from distributed_learning_simulator_tpu.sweep.spec import SweepSpec

    config = get_config(argv)
    if SweepSpec.active(config):
        # Sweep knobs set (sweep_seeds / sweep_points): the process runs
        # a FLEET of experiments instead of one (sweep/engine.py).
        return run_sweep(config)
    result = run_simulation(config)
    return result


if __name__ == "__main__":
    main()
