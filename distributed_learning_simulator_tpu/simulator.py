"""Simulation orchestrator: the TPU-native ``simulator.py``.

Replaces the reference entry point (reference simulator.py:33-72): where the
reference builds a thread pool, a queue-owning server, and one worker thread
per client, this builds

  dataset -> client partition (packed client axis) -> model/optimizer ->
  algorithm strategy -> ONE jitted round function -> host round loop.

The host loop only sequences rounds, evaluates the global model once per
round (parity with fed_server.py:85-86), logs, checkpoints, and runs the
algorithm's host-side post_round hook (Shapley). All training compute for all
clients in a round is a single XLA program launch.

Multi-chip: set ``config.mesh_devices`` — the packed client arrays and
per-client state get ``PartitionSpec("clients")`` over a 1-D mesh and the
same program runs SPMD; weighted-mean/vote reductions become ICI collectives.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.algorithms.base import RoundContext
from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.partition import (
    ClientData,
    dirichlet_partition,
    iid_partition,
    pack_client_shards,
)
from distributed_learning_simulator_tpu.data.registry import Dataset, get_dataset
from distributed_learning_simulator_tpu.factory import get_algorithm
from distributed_learning_simulator_tpu.models.registry import get_model, init_params
from distributed_learning_simulator_tpu.parallel.engine import (
    make_decoder,
    make_eval_fn,
    make_optimizer,
    pad_eval_set,
)
from distributed_learning_simulator_tpu.parallel.mesh import (
    make_mesh,
    replicate,
    shard_client_data,
)
from distributed_learning_simulator_tpu.utils.checkpoint import (
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
)
from distributed_learning_simulator_tpu.utils.logging import (
    get_logger,
    set_file_handler,
    set_level,
)
from distributed_learning_simulator_tpu.utils.tracing import (
    annotate,
    profile_session,
)


def build_client_data(config: ExperimentConfig, dataset: Dataset) -> ClientData:
    """Partition the training set into the packed client axis."""
    if config.partition == "iid":
        indices = iid_partition(
            len(dataset.x_train), config.worker_number, seed=config.seed
        )
    else:
        indices = dirichlet_partition(
            dataset.y_train, config.worker_number, config.dirichlet_alpha,
            seed=config.seed,
        )
    if config.max_shard_size:
        # Unbiased cap: partition index lists are dataset-ordered, so a
        # plain [:cap] would keep only low-index samples (dropping whole
        # classes on class-ordered datasets).
        rng = np.random.default_rng(config.seed + 17)
        indices = [
            rng.permutation(ix)[: config.max_shard_size] for ix in indices
        ]
    return pack_client_shards(
        dataset.x_train, dataset.y_train, indices,
        batch_size=config.batch_size,
        compact=config.compact_client_data,
    )


def run_simulation(
    config: ExperimentConfig,
    dataset: Dataset | None = None,
    client_data: ClientData | None = None,
    setup_logging: bool = True,
):
    """Run the full federated simulation; returns a result dict.

    ``dataset``/``client_data`` injection points cover the reference's
    heterogeneous-data variant (simulator_backup.py:71-77): build
    ``client_data`` yourself, call ``client_data.override_client(0, ...)``,
    and pass it in.
    """
    config.validate()
    logger = get_logger()
    set_level(config.log_level)
    log_dir = None
    if setup_logging:
        log_path = set_file_handler(
            config.log_root, config.distributed_algorithm,
            config.dataset_name, config.model_name,
        )
        # Per-run artifact dir: Shapley metric pickles etc. go here so
        # concurrent/subsequent runs never overwrite each other's artifacts.
        log_dir = log_path[: -len(".log")] + "_artifacts"
        logger.info("log file: %s", log_path)

    # --- data ---------------------------------------------------------------
    if dataset is None:
        dataset = get_dataset(
            config.dataset_name, data_dir=config.data_dir, seed=config.seed,
            n_train=config.n_train, n_test=config.n_test,
            **config.dataset_args,
        )
    if client_data is None:
        client_data = build_client_data(config, dataset)
    n_clients = client_data.n_clients
    eval_batches_np = pad_eval_set(
        dataset.x_test, dataset.y_test, config.eval_batch_size
    )

    # --- model / optimizer / algorithm --------------------------------------
    model = get_model(config.model_name, num_classes=dataset.num_classes)
    global_params = init_params(model, dataset.x_train[:1], seed=config.seed)
    optimizer = make_optimizer(
        config.optimizer_name, config.learning_rate,
        momentum=config.momentum, weight_decay=config.weight_decay,
    )
    algorithm = get_algorithm(config.distributed_algorithm, config)

    evaluate = jax.jit(make_eval_fn(model.apply))
    algorithm.prepare(model.apply, make_eval_fn(model.apply))
    preprocess = (
        make_decoder(client_data.sample_shape) if client_data.compact else None
    )
    round_fn = algorithm.make_round_fn(
        model.apply, optimizer, n_clients, preprocess=preprocess
    )
    round_jit = jax.jit(round_fn, donate_argnums=(1,))

    # --- resume (before placement, so restored state gets sharded too) ------
    start_round = 0
    prev_metrics: dict | None = None
    key = jax.random.key(config.seed + 1)
    client_state = algorithm.init_client_state(
        optimizer, global_params, n_clients
    )
    if config.resume and config.checkpoint_dir:
        ckpt_path = latest_checkpoint(config.checkpoint_dir)
        if ckpt_path:
            ckpt = load_checkpoint(ckpt_path)
            global_params = jax.tree_util.tree_map(
                jnp.asarray, ckpt["global_params"]
            )
            client_state = jax.tree_util.tree_map(
                jnp.asarray, ckpt["client_state"]
            )
            start_round = ckpt["round_idx"] + 1
            prev_metrics = ckpt["algo_state"].get("prev_metrics")
            if ckpt.get("rng_key") is not None:
                key = ckpt["rng_key"]
            if hasattr(algorithm, "shapley_values"):
                algorithm.shapley_values.update(
                    ckpt["algo_state"].get("shapley_values", {})
                )
            logger.info("resumed from %s at round %d", ckpt_path, start_round)

    # --- placement ----------------------------------------------------------
    mesh = None
    data_arrays = (
        jnp.asarray(client_data.x), jnp.asarray(client_data.y),
        jnp.asarray(client_data.mask),
    )
    sizes = jnp.asarray(client_data.sizes)
    eval_batches = tuple(jnp.asarray(a) for a in eval_batches_np)
    if config.mesh_devices and config.mesh_devices > 1:
        mesh = make_mesh(config.mesh_devices)
        if n_clients % config.mesh_devices != 0:
            raise ValueError(
                f"worker_number ({n_clients}) must be a multiple of "
                f"mesh_devices ({config.mesh_devices})"
            )
        data_arrays = shard_client_data(data_arrays, mesh)
        client_state = shard_client_data(client_state, mesh)
        global_params = replicate(global_params, mesh)
        sizes = replicate(sizes, mesh)
        eval_batches = replicate(eval_batches, mesh)
        logger.info("client axis sharded over %d devices", config.mesh_devices)
    cx, cy, cmask = data_arrays

    # --- round loop ---------------------------------------------------------
    history: list[dict] = []
    metrics_path = None
    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
        metrics_path = os.path.join(log_dir, "metrics.jsonl")
    t_start = time.perf_counter()
    with profile_session(config.profile_dir):
        for round_idx in range(start_round, config.round):
            key, round_key = jax.random.split(key)
            t0 = time.perf_counter()
            with annotate(f"fl_round_{round_idx}"):
                new_global, client_state, aux = round_jit(
                    global_params, client_state, cx, cy, cmask, sizes,
                    round_key,
                )
            with annotate("server_eval"):
                metrics_dev = evaluate(new_global, *eval_batches)
            metrics = {k: float(v) for k, v in metrics_dev.items()}
            round_time = time.perf_counter() - t0

            ctx = RoundContext(
                round_idx=round_idx,
                global_params=new_global,
                prev_global_params=global_params,
                sizes=sizes,
                aux=aux,
                metrics=metrics,
                prev_metrics=prev_metrics,
                eval_batches=eval_batches,
                log_dir=log_dir,
            )
            with annotate("post_round"):
                extra = algorithm.post_round(ctx) or {}
            record = {
                "round": round_idx,
                "test_accuracy": metrics["accuracy"],
                "test_loss": metrics["loss"],
                "mean_client_loss": float(aux.get("mean_client_loss", np.nan)),
                "round_seconds": round_time,
                **{
                    k: v for k, v in extra.items()
                    if isinstance(v, (int, float, dict))
                },
            }
            history.append(record)
            if metrics_path:
                with open(metrics_path, "a") as f:
                    f.write(json.dumps(record) + "\n")
            logger.info(
                "round %d: test_acc=%.4f test_loss=%.4f (%.2fs)",
                round_idx, metrics["accuracy"], metrics["loss"], round_time,
            )
            global_params = new_global
            prev_metrics = metrics

            if (
                config.checkpoint_dir
                and config.checkpoint_every
                and (round_idx + 1) % config.checkpoint_every == 0
            ):
                algo_state = {"prev_metrics": metrics}
                if hasattr(algorithm, "shapley_values"):
                    algo_state["shapley_values"] = algorithm.shapley_values
                save_checkpoint(
                    os.path.join(
                        config.checkpoint_dir, f"round_{round_idx}.ckpt"
                    ),
                    round_idx, global_params, client_state, algo_state, key,
                )

    total = time.perf_counter() - t_start
    n_rounds = config.round - start_round
    logger.info(
        "finished %d rounds x %d clients in %.2fs (%.1f client-rounds/sec)",
        n_rounds, n_clients, total,
        n_rounds * n_clients / max(total, 1e-9),
    )
    return {
        "global_params": global_params,
        "client_state": client_state,
        "history": history,
        "algorithm": algorithm,
        "final_accuracy": history[-1]["test_accuracy"] if history else None,
        "total_seconds": total,
        "client_rounds_per_sec": n_rounds * n_clients / max(total, 1e-9),
        "mesh": mesh,
    }


def main(argv: list[str] | None = None):
    from distributed_learning_simulator_tpu.config import get_config

    config = get_config(argv)
    result = run_simulation(config)
    return result


if __name__ == "__main__":
    main()
