"""Heterogeneous-data entry point: one client trains on a "bad" dataset.

Parity with the reference's ``simulator_backup.py`` experiment
(simulator_backup.py:50-53,71-77): worker 0's training shard is replaced with
a different, grayscale-converted dataset (channel-tiled back to the packed
array's channel count) while workers 1..N-1 keep IID shards of the configured
dataset. Demonstrates the framework's per-client dataset override — the
generic injection point is ``ClientData.override_client``.

Usage (same CLI as the main simulator, plus --bad_dataset_name):

    python -m distributed_learning_simulator_tpu.simulator_heterogeneous \
        --dataset_name cifar10 --model_name cnn --distributed_algorithm fed \
        --worker_number 4 --round 5 --epoch 1 --learning_rate 0.1
"""

from __future__ import annotations

import numpy as np

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.registry import get_dataset
from distributed_learning_simulator_tpu.simulator import (
    build_client_data,
    run_simulation,
)
from distributed_learning_simulator_tpu.utils.logging import get_logger


def run_heterogeneous(
    config: ExperimentConfig,
    bad_dataset_name: str = "mnist",
    bad_client_id: int = 0,
):
    """Run the simulation with ``bad_client_id``'s shard swapped out."""
    dataset = get_dataset(
        config.dataset_name, data_dir=config.data_dir, seed=config.seed,
        n_train=config.n_train, n_test=config.n_test, **config.dataset_args,
    )
    client_data = build_client_data(config, dataset)

    # The "bad" dataset: grayscale (dataset_args parity with
    # simulator_backup.py:50 to_grayscale=True), resized by channel tiling to
    # match the packed array's shape.
    bad = get_dataset(
        bad_dataset_name, data_dir=config.data_dir, seed=config.seed + 1,
        n_train=client_data.shard_size, to_grayscale=True,
    )
    target_shape = client_data.sample_shape or client_data.x.shape[2:]
    bad_x = _fit_images(bad.x_train, target_shape)
    get_logger().info(
        "client %d gets %d samples of bad dataset %r (others keep %s shards)",
        bad_client_id, len(bad_x), bad_dataset_name, config.dataset_name,
    )
    client_data.override_client(bad_client_id, bad_x, bad.y_train)
    return run_simulation(config, dataset=dataset, client_data=client_data)


def _fit_images(x: np.ndarray, shape) -> np.ndarray:
    """Crop/pad spatially and tile channels so ``x`` fits ``shape``."""
    h, w, c = shape
    out = np.zeros((x.shape[0], h, w, c), dtype=np.float32)
    hh, ww = min(h, x.shape[1]), min(w, x.shape[2])
    src = x[:, :hh, :ww, :]
    if src.shape[-1] == 1 and c > 1:
        src = np.repeat(src, c, axis=-1)
    out[:, :hh, :ww, : src.shape[-1]] = src[..., :c]
    return out


def main(argv: list[str] | None = None):
    import argparse

    from distributed_learning_simulator_tpu.config import get_config

    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument("--bad_dataset_name", type=str, default="mnist")
    pre.add_argument("--bad_client_id", type=int, default=0)
    known, rest = pre.parse_known_args(argv)
    config = get_config(rest)
    return run_heterogeneous(
        config, bad_dataset_name=known.bad_dataset_name,
        bad_client_id=known.bad_client_id,
    )


if __name__ == "__main__":
    main()
