from distributed_learning_simulator_tpu.execution.threaded import (
    ThreadedServer,
    ThreadedWorker,
    run_threaded_simulation,
)

__all__ = ["ThreadedServer", "ThreadedWorker", "run_threaded_simulation"]
