"""Threaded execution mode: architecture parity with the reference.

The reference runs one OS thread per client, rendezvousing with a server
through a blocking queue (reference simulator.py:60-69, servers/server.py:
10-17, workers/fed_worker.py:19-38). The framework's fast path replaces all
of that with one XLA program (parallel/engine.py) — but the thread/queue
architecture remains useful as an *escape hatch*: per-client logic that
cannot be vmapped (arbitrary Python callbacks, per-client model surgery,
external I/O mid-round). This module provides that mode, backed by the
native C++ runtime (runtime/native.py).

Structure mirrors the reference exactly:

  * :class:`ThreadedServer` owns the rendezvous queue constructed with
    ``worker_fun=self._process_worker_data`` (servers/server.py:10-17) and
    seeds it with the initial global params broadcast N times
    (fed_server.py:16-24). The worker_fun buffers per-client uploads, and on
    the Nth arrival aggregates (dataset-size-weighted mean,
    fed_server.py:44-66,81), evaluates (fed_server.py:85-86), and broadcasts
    (fed_server.py:88-91). Template hooks ``_process_client_parameter`` /
    ``_process_aggregated_parameter`` are overridable (fed_server.py:38-42).
  * :class:`ThreadedWorker` blocks for the global params, runs E local
    epochs via the SAME jitted local_train the vmap path uses (one
    compilation shared by every thread), and uploads
    ``(worker_id, dataset_size, params)`` (fed_worker.py:19-38).
  * :class:`ThreadedSignSGDServer` / :class:`ThreadedSignSGDWorker` carry
    the reference's finest-grained queue contract — per-OPTIMIZER-STEP
    sign-gradient sync (sign_sgd_worker.py:44-47: submit signs, block for
    the majority vote, apply locally) — with the reference's mis-wired vote
    method fixed (SURVEY 2.1#13). Because every worker applies the same
    voted update, all workers hold identical params after every step; the
    server maintains its own replica by applying the votes too, which lets
    it evaluate and record per-round metrics without extra message types.

Rounds are synchronized at round granularity for FedAvg, at step
granularity for SignSGD — exactly like the reference workers.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.config import ExperimentConfig
from distributed_learning_simulator_tpu.data.partition import ClientData
from distributed_learning_simulator_tpu.data.registry import Dataset, get_dataset
from distributed_learning_simulator_tpu.models.registry import get_model, init_params
from distributed_learning_simulator_tpu.ops.aggregate import aggregate
from distributed_learning_simulator_tpu.parallel.engine import (
    make_decoder,
    make_eval_fn,
    make_local_train_fn,
    make_optimizer,
    pad_eval_set,
)
from distributed_learning_simulator_tpu.runtime.native import (
    NativeTaskQueue,
    NativeThreadPool,
)
from distributed_learning_simulator_tpu.telemetry import (
    ClientStats,
    RecompileMonitor,
    detect_and_record,
    make_phase_timer,
    peak_hbm_bytes,
)
from distributed_learning_simulator_tpu.utils.logging import get_logger
from distributed_learning_simulator_tpu.utils.reporting import (
    build_round_record,
)


class _QueueServerBase:
    """Shared rendezvous plumbing for the threaded servers.

    Downlink routing deviates from the reference deliberately: the
    reference broadcasts N copies into ONE shared result pool
    (RepeatedResult, fed_server.py:88-91), which has a copy-stealing race —
    a fast worker that finishes its next local run before a descheduled
    peer pops its copy can consume the peer's stale copy as if it were the
    next rendezvous' broadcast, desynchronizing the two and deadlocking
    the barrier. Results are routed per worker here (one downlink queue
    each, same blocking-rendezvous contract); the shared uplink queue and
    its worker_fun callback remain exactly the reference's shape."""

    worker_number: int

    def _init_queues(self) -> None:
        self.server_error: BaseException | None = None
        # Run telemetry (docs/OBSERVABILITY.md): the serve thread times its
        # aggregate/eval/post_round work per round, same phase vocabulary
        # as the vmap path ('client_step' has no server-side analogue here
        # — local training runs on the worker threads).
        self._phase_timer = make_phase_timer(self.config.telemetry_level)
        # Per-client statistics (telemetry/client_stats.py): the threaded
        # server holds the full upload stack at its rendezvous barrier, so
        # the stats come straight off it. Its workers report no losses —
        # those columns are NaN (rendered null) and the detector skips
        # them; the update-norm / cosine / non-finite columns and the
        # flagging flow through the same shared record builder as the
        # vmap path. None at the default 'off'.
        self._client_stats = ClientStats.from_config(self.config)
        # Run total for the result dict, mirroring the vmap path's
        # clients_flagged contract.
        self.clients_flagged = 0
        self.result_queues = [
            NativeTaskQueue() for _ in range(self.worker_number)
        ]
        self.worker_data_queue = NativeTaskQueue(
            worker_fun=self._guarded_worker_fun
        )

    def _finish_record(self, record: dict, round_idx: int,
                       client_stats: dict | None = None) -> dict:
        """Fold the round's telemetry + client stats into the metrics
        record through the shared schema-versioned builder
        (utils/reporting.py); with telemetry_level='off' and no client
        stats the legacy v1 record passes through unchanged."""
        tel = None
        if self._phase_timer.enabled:
            tel = {
                "phase_seconds": {
                    k: round(v, 6)
                    for k, v in sorted(
                        self._phase_timer.take(round_idx).items()
                    )
                },
            }
            peak = peak_hbm_bytes()
            if peak is not None:
                tel["peak_hbm_bytes"] = peak
        if tel is None and client_stats is None:
            return record
        return build_round_record(record, tel, client_stats)

    def _guarded_worker_fun(self, data, extra_args):
        """Server-callback errors must tear the rendezvous down, not kill
        the serve thread silently: an eval OOM or a full disk inside
        _process_worker_data would otherwise leave every worker blocked on
        a broadcast that never comes (and the coordinator's progress poll
        spinning forever). Record the error, stop every queue so blocked
        workers unblock with 'queue is stopped', and let the coordinator
        re-raise the ORIGINAL error."""
        try:
            return self._process_worker_data(data, extra_args)
        except BaseException as e:  # noqa: BLE001 - re-raised by coordinator
            self.server_error = e
            self.stop()
            return None

    def _process_worker_data(self, data, extra_args):  # pragma: no cover
        raise NotImplementedError

    def _broadcast(self, payload) -> None:
        # Serialize once, enqueue the same bytes N times (a per-queue
        # put_result would re-pickle the full model per worker — per STEP
        # for sign_SGD).
        blob = pickle.dumps(payload)
        for q in self.result_queues:
            try:
                q.put_result_pickled(blob)
            except RuntimeError:
                # Swallow ONLY the stopped-queue race (stop() raced the
                # final broadcast; nobody is listening). Any other enqueue
                # failure would leave some workers with the payload and
                # others without — that must propagate, not vanish.
                if not q.stopped:
                    raise

    def stop(self):
        self.worker_data_queue.stop()
        for q in self.result_queues:
            q.stop()


class ThreadedServer(_QueueServerBase):
    """Queue-owning server (reference servers/server.py + fed_server.py)."""

    def __init__(self, config: ExperimentConfig, evaluate, eval_batches,
                 init_params_tree, metrics_path: str | None = None):
        self.config = config
        self.worker_number = config.worker_number
        self._evaluate = evaluate
        self._eval_batches = eval_batches
        self._buffer: dict[int, tuple[float, dict]] = {}
        self._round = 0
        self.history: list[dict] = []
        self.metrics_path = metrics_path
        self.prev_model = init_params_tree
        self._round_t0 = time.perf_counter()
        self._init_queues()
        # Seed the initial broadcast (fed_server.py:16-24).
        self._broadcast(jax.device_get(init_params_tree))

    # Template hooks (fed_server.py:38-42).
    def _process_client_parameter(self, worker_id: int, params):
        return params

    def _process_aggregated_parameter(self, params):
        return params

    def _record_extra(self, aggregated) -> dict:
        """Algorithm-specific per-round history fields (FedQuant adds its
        compression telemetry here)."""
        del aggregated
        return {}

    def _post_round(self, stacked, sizes, aggregated, metrics) -> dict:
        """Server-side post-round hook with the full per-client parameter
        stack (the Shapley servers score contributions here, parity with
        the reference's post-aggregation hooks). Returns extra per-round
        record fields."""
        del stacked, sizes, aggregated, metrics
        return {}

    def _process_worker_data(self, data, extra_args):
        del extra_args
        worker_id, dataset_size, params = data
        self._buffer[worker_id] = (
            dataset_size, self._process_client_parameter(worker_id, params)
        )
        if len(self._buffer) < self.worker_number:
            return None  # barrier: wait for all clients (fed_server.py:75-77)
        with self._phase_timer.phase(self._round, "aggregate") as _ph:
            stacked = jax.tree_util.tree_map(
                lambda *xs: jnp.stack(xs),
                *[self._buffer[i][1] for i in range(self.worker_number)],
            )
            sizes = jnp.asarray(
                [self._buffer[i][0] for i in range(self.worker_number)],
                dtype=jnp.float32,
            )
            aggregated = aggregate(
                stacked, sizes, self.config.aggregation, self.config.trim_ratio
            )
            if self.config.aggregation.lower() != "mean":
                # Same finite-or-previous-model guard as the vmap path
                # (fedavg.py round_fn): an all-diverged cohort must not
                # poison the global model — the two execution modes are a
                # differential oracle pair and must agree in exactly these
                # scenarios. One fused reduction + one device sync (a
                # per-leaf bool() would pay L round-trips per round, and
                # params are normally finite so every leaf would be
                # fetched).
                finite = bool(jnp.all(jnp.stack([
                    jnp.all(jnp.isfinite(leaf.astype(jnp.float32)))
                    for leaf in jax.tree_util.tree_leaves(aggregated)
                ])))
                if not finite:
                    aggregated = self.prev_model
            cs_rec = None
            if (
                self._client_stats is not None
                and self._client_stats.fetch_round(self._round)
            ):
                # Stats on the raw (pre-downlink) aggregate, same point
                # as the vmap path's probe; the threaded oracle refuses
                # failure models, so this is diagnostics, not defense.
                cs_rec, n_flagged = detect_and_record(
                    jax.device_get(self._client_stats.stack_stats(
                        self.prev_model, stacked, aggregated
                    )),
                    self._client_stats, self._round, logger=get_logger(),
                )
                self.clients_flagged += n_flagged
            aggregated = self._process_aggregated_parameter(aggregated)
            _ph.fence(aggregated)
        with self._phase_timer.phase(self._round, "eval"):
            # float() blocks on the device values, so the phase needs no
            # explicit fence even under 'detailed'.
            metrics = {
                k: float(v)
                for k, v in self._evaluate(
                    aggregated, *self._eval_batches
                ).items()
            }
        with self._phase_timer.phase(self._round, "post_round"):
            extra_post = self._post_round(stacked, sizes, aggregated, metrics)
        record = {
            "round": self._round,
            "test_accuracy": metrics["accuracy"],
            "test_loss": metrics["loss"],
            "round_seconds": time.perf_counter() - self._round_t0,
            **self._record_extra(aggregated),
            **extra_post,
        }
        record = self._finish_record(record, self._round,
                                     client_stats=cs_rec)
        self.history.append(record)
        if self.metrics_path:
            with open(self.metrics_path, "a") as f:
                f.write(json.dumps(record) + "\n")
        get_logger().info(
            "threaded round %d: test_acc=%.4f test_loss=%.4f",
            self._round, metrics["accuracy"], metrics["loss"],
        )
        self.prev_model = aggregated
        self._round += 1
        self._round_t0 = time.perf_counter()
        self._buffer.clear()
        self._broadcast(jax.device_get(aggregated))
        return None


class ThreadedWorker:
    """One simulated client on its own thread (reference workers/fed_worker.py)."""

    def __init__(self, worker_id: int, queue: NativeTaskQueue,
                 result_queue: NativeTaskQueue, local_train, shard,
                 rounds: int, seed: int):
        self.worker_id = worker_id
        self.queue = queue
        self.result_queue = result_queue
        self._local_train = local_train
        self._shard = shard  # (xs, ys, mask, size)
        self._rounds = rounds
        self._seed = seed

    def train(self):
        xs, ys, mask, size = self._shard
        key = jax.random.key(self._seed * 100003 + self.worker_id)
        for _ in range(self._rounds):
            # Block for the current global model (fed_worker.py:22,37).
            params = self.result_queue.get_result()
            params = jax.tree_util.tree_map(jnp.asarray, params)
            key, round_key, upload_key = jax.random.split(key, 3)
            new_params, _, _ = self._local_train(
                params, None, xs, ys, mask, round_key
            )
            # Upload (worker_id, |D_i|, params) (fed_worker.py:28-35).
            self.queue.add_task((
                self.worker_id, size,
                jax.device_get(self._upload_payload(new_params, upload_key)),
            ))

    def _upload_payload(self, new_params, key):
        """Uplink transform hook (identity; FedQuant quantizes)."""
        del key
        return new_params


class ThreadedFedQuantServer(ThreadedServer):
    """Quantized-exchange FedAvg through the queue architecture (reference
    servers/fed_quant_server.py): clients upload 8-bit stochastically
    quantized params, the server dequantizes each upload before the
    weighted mean (:25-33), re-quantizes the aggregate for the downlink
    (:35-50), and reports the compression ratio per round. The quantize/
    dequantize math is ops/quantize.py — the single source shared with the
    vmap FedQuant, so the two execution modes form a differential oracle
    for the quantized exchange path.

    The downlink broadcast carries the DEQUANTIZED values: in the reference
    too, dequantization runs in server code when the worker calls
    ``get_parameter_dict()`` over shared memory (fed_quant_server.py:20-24)
    — the quantized pair never crosses a wire the worker decodes itself."""

    def __init__(self, config: ExperimentConfig, evaluate, eval_batches,
                 init_params_tree, metrics_path: str | None = None):
        from distributed_learning_simulator_tpu.ops.quantize import (
            dequantize_tree,
            stochastic_quantize_tree,
        )

        self._levels = getattr(config, "quant_levels", 256)
        self._quant_key = jax.random.key(config.seed + 9973)
        self._dequantize_tree = dequantize_tree
        self._quantize_tree = stochastic_quantize_tree
        super().__init__(config, evaluate, eval_batches, init_params_tree,
                         metrics_path=metrics_path)

    def _process_client_parameter(self, worker_id: int, params):
        # Uplink: the client sent QuantizedTensor leaves; reconstruct f32
        # values before aggregation (fed_quant_server.py:25-33).
        del worker_id
        return self._dequantize_tree(params)

    def _process_aggregated_parameter(self, params):
        # Downlink: unbiased stochastic re-quantization of the aggregate
        # (fed_quant_server.py:35-39), dequantized for the broadcast.
        self._quant_key, k = jax.random.split(self._quant_key)
        return self._dequantize_tree(
            self._quantize_tree(params, self._levels, k)
        )

    def _record_extra(self, aggregated) -> dict:
        # Analytic compression telemetry, same fields as the vmap FedQuant's
        # post_round (parity with the serialized-size logs at
        # fed_quant_server.py:41-48).
        from distributed_learning_simulator_tpu.ops.payload import (
            compression_ratio,
            payload_bytes,
            quantized_payload_bytes,
        )

        raw = payload_bytes(aggregated)
        comp = quantized_payload_bytes(aggregated, self._levels)
        ratio = compression_ratio(raw, comp)
        return {
            "uplink_compression_ratio": ratio,
            "downlink_compression_ratio": ratio,
        }


class ThreadedFedQuantWorker(ThreadedWorker):
    """FedQuant client thread: QAT local training (the shared jitted
    local_train carries the fake-quant param transform), then a genuinely
    quantized upload — the payload on the uplink queue is the
    QuantizedTensor tree, decoded server-side (reference
    fed_quant_worker.py:36-53 sends the QAT-quantized parameter dict)."""

    def __init__(self, *args, levels: int = 256):
        super().__init__(*args)
        self._levels = levels
        from distributed_learning_simulator_tpu.ops.quantize import (
            stochastic_quantize_tree,
        )

        self._quantize_tree = stochastic_quantize_tree

    def _upload_payload(self, new_params, key):
        return self._quantize_tree(new_params, self._levels, key)


class ThreadedShapleyServer(ThreadedServer):
    """Shapley contribution scoring through the queue architecture
    (reference servers/multiround_shapley_value_server.py and
    GTG_shapley_value_server.py both extend the queue-owning FedServer).

    The server-side post-aggregation hook scores each client from the
    full per-client upload stack, REUSING the same algorithm strategy
    objects — and their wave-batched, memoized subset evaluator — as the
    vmap path (algorithms/shapley.py), so the two execution modes share
    one implementation of the scoring math."""

    def __init__(self, config: ExperimentConfig, evaluate, eval_batches,
                 init_params_tree, algorithm, log_dir: str | None = None,
                 metrics_path: str | None = None):
        self._shapley = algorithm
        self._prev_metrics: dict | None = None
        self._log_dir = log_dir
        super().__init__(config, evaluate, eval_batches, init_params_tree,
                         metrics_path=metrics_path)

    def _post_round(self, stacked, sizes, aggregated, metrics) -> dict:
        from distributed_learning_simulator_tpu.algorithms.base import (
            RoundContext,
        )

        ctx = RoundContext(
            round_idx=self._round,
            global_params=aggregated,
            # prev_model is updated AFTER the record is built, so at hook
            # time it still holds the round's broadcast source — the
            # empty-coalition model the subset utilities fall back to.
            prev_global_params=self.prev_model,
            sizes=sizes,
            aux={"client_params": stacked},
            metrics=metrics,
            prev_metrics=self._prev_metrics,
            eval_batches=self._eval_batches,
            log_dir=self._log_dir,
        )
        extra = self._shapley.post_round(ctx) or {}
        self._prev_metrics = metrics
        return {
            k: v for k, v in extra.items()
            if isinstance(v, (int, float, dict))
        }


class ThreadedSignSGDServer(_QueueServerBase):
    """Per-step majority-vote server (reference servers/sign_sgd_server.py,
    with the vote actually wired to the queue callback — the reference's
    name-mangled ``__worker`` is dead code, SURVEY 2.1#13).

    Buffers each worker's per-step sign gradients; on the Nth arrival sums
    elementwise and re-signs (sign_sgd_server.py:16-18), broadcasts the vote
    to every worker, and applies the vote to its own params replica — valid
    because every worker applies the identical update, so server and
    workers stay in bitwise lockstep (same jitted apply). At round
    boundaries (every ``steps_per_round`` votes) it evaluates the replica
    and records the per-round history the differential-testing oracle
    compares.

    Votes are routed per worker (one downlink queue each) rather than N
    copies in one shared pool: per-step sync re-runs the rendezvous
    thousands of times per run, so the shared-pool copy-stealing race (see
    _QueueServerBase) would be an eventual deadlock, not a curiosity."""

    def __init__(self, config: ExperimentConfig, evaluate, eval_batches,
                 init_params_tree, apply_vote, steps_per_round: int,
                 metrics_path: str | None = None):
        self.config = config
        self.worker_number = config.worker_number
        self._evaluate = evaluate
        self._eval_batches = eval_batches
        self._apply_vote = apply_vote
        self._steps_per_round = steps_per_round
        self._buffer: dict[int, Any] = {}
        self._step = 0
        self.history: list[dict] = []
        self.metrics_path = metrics_path
        self.params = init_params_tree
        self._round_t0 = time.perf_counter()
        self._init_queues()
        # No initial broadcast: the reference SignSGDServer extends the bare
        # Server (no FedServer param seeding); workers start from the same
        # deterministic init instead.

    def _process_worker_data(self, data, extra_args):
        del extra_args
        worker_id, signs = data
        self._buffer[worker_id] = signs
        if len(self._buffer) < self.worker_number:
            return None  # barrier: every step waits for all N workers
        # Per-step vote + apply accumulate into the CURRENT round's
        # 'aggregate' phase (sign_SGD aggregates per optimizer step, so
        # the round's phase time is the sum of its steps' votes).
        with self._phase_timer.phase(
                self._step // self._steps_per_round, "aggregate") as _ph:
            # Majority vote: elementwise sign of the summed signs.
            voted = jax.tree_util.tree_map(
                lambda *xs: np.sign(np.sum(np.stack(xs), axis=0)),
                *[self._buffer[i] for i in range(self.worker_number)],
            )
            self._buffer.clear()
            self.params = self._apply_vote(
                self.params, jax.tree_util.tree_map(jnp.asarray, voted)
            )
            _ph.fence(self.params)
        self._step += 1
        if self._step % self._steps_per_round == 0:
            round_idx = self._step // self._steps_per_round - 1
            with self._phase_timer.phase(round_idx, "eval"):
                metrics = {
                    k: float(v)
                    for k, v in self._evaluate(
                        self.params, *self._eval_batches
                    ).items()
                }
            from distributed_learning_simulator_tpu.ops.payload import (
                compression_ratio,
                payload_bytes,
                sign_payload_bytes,
            )

            raw = payload_bytes(self.params)
            record = {
                "round": round_idx,
                "test_accuracy": metrics["accuracy"],
                "test_loss": metrics["loss"],
                "round_seconds": time.perf_counter() - self._round_t0,
                "uplink_compression_ratio": compression_ratio(
                    raw, sign_payload_bytes(self.params)
                ),
                "sync_steps": self._steps_per_round,
            }
            record = self._finish_record(record, round_idx)
            self.history.append(record)
            if self.metrics_path:
                with open(self.metrics_path, "a") as f:
                    f.write(json.dumps(record) + "\n")
            get_logger().info(
                "threaded round %d: test_acc=%.4f test_loss=%.4f",
                round_idx, metrics["accuracy"], metrics["loss"],
            )
            self._round_t0 = time.perf_counter()
        self._broadcast(voted)
        return None


class ThreadedSignSGDWorker:
    """One SignSGD client on its own thread (reference
    workers/sign_sgd_worker.py): per optimizer step, compute the effective
    SGD direction (torch momentum math incl. buf=grad first step, :22-42),
    sign it, submit, block for the vote, apply locally (:44-58)."""

    def __init__(self, worker_id: int, queue: NativeTaskQueue,
                 result_queue: NativeTaskQueue, direction_fn,
                 apply_vote, shard, init_params_tree, rounds: int,
                 epochs: int, batch_size: int, seed: int):
        self.worker_id = worker_id
        self.queue = queue
        self.result_queue = result_queue
        self._direction = direction_fn
        self._apply_vote = apply_vote
        self._shard = shard  # (xs, ys, mask, size)
        self._init_params = init_params_tree
        self._rounds = rounds
        self._epochs = epochs
        self._batch_size = batch_size
        self._seed = seed

    def train(self):
        xs, ys, mask, _size = self._shard
        params = jax.tree_util.tree_map(jnp.asarray, self._init_params)
        momenta = jax.tree_util.tree_map(jnp.zeros_like, params)
        shard_size = xs.shape[0]
        steps_per_epoch = shard_size // self._batch_size
        rng = np.random.default_rng(self._seed * 100003 + self.worker_id)
        is_first = True
        for _ in range(self._rounds):
            for _ in range(self._epochs):
                perm = rng.permutation(shard_size)
                for s in range(steps_per_epoch):
                    idx = perm[s * self._batch_size:(s + 1) * self._batch_size]
                    signs, momenta = self._direction(
                        params, momenta, jnp.asarray(is_first),
                        xs[idx], ys[idx], mask[idx],
                    )
                    is_first = False
                    self.queue.add_task(
                        (self.worker_id, jax.device_get(signs))
                    )
                    voted = self.result_queue.get_result()
                    params = self._apply_vote(
                        params, jax.tree_util.tree_map(jnp.asarray, voted)
                    )


def run_threaded_simulation(
    config: ExperimentConfig,
    dataset: Dataset | None = None,
    client_data: ClientData | None = None,
    setup_logging: bool = True,
):
    """Run FedAvg or SignSGD in thread-per-client mode; returns a result
    dict.

    Semantically equivalent to ``run_simulation`` with the same algorithm
    (client batch order differs, so trajectories match statistically, not
    bitwise) — the two execution modes are a differential-testing oracle
    pair.
    """
    from distributed_learning_simulator_tpu.simulator import build_client_data

    config.validate()
    algo_name = config.distributed_algorithm
    supported = ("fed", "sign_SGD", "fed_quant", "multiround_shapley_value",
                 "GTG_shapley_value")
    if algo_name not in supported:
        raise ValueError(
            f"threaded execution mode supports {supported}, not "
            f"{algo_name!r}"
        )
    if algo_name == "sign_SGD":
        # Constructor runs the sign_SGD config validation (requires SGD,
        # no augmentation, mean aggregation) — shared with the vmap path.
        from distributed_learning_simulator_tpu.algorithms.sign_sgd import (
            SignSGD,
        )

        SignSGD(config)
    if algo_name == "multiround_shapley_value":
        # Constructor runs the exact-Shapley N <= 16 bound up-front
        # (MultiRoundShapley.__init__): without it, the failure would
        # surface only inside the round-0 server callback — after threads
        # spawn and a full round of local training has run.
        from distributed_learning_simulator_tpu.algorithms.shapley import (
            MultiRoundShapley,
        )

        MultiRoundShapley(config)
    if config.server_optimizer_name.lower() not in ("none", ""):
        raise ValueError(
            "threaded execution mode does not support server optimizers; "
            "use run_simulation for FedAvgM/FedAdam"
        )
    if config.participation_fraction < 1.0:
        # Thread-per-client barriers on every worker (the reference's
        # behavior); sampling would be silently ignored — reject instead.
        raise ValueError(
            "threaded execution mode trains all clients every round; "
            "participation_fraction < 1 requires the vmap execution mode"
        )
    if (config.checkpoint_dir and config.checkpoint_every) or config.resume:
        # Long-job persistence is wired into the vmap round loop only;
        # silently dropping it would lose a crashed run's progress.
        raise ValueError(
            "threaded execution mode does not support checkpoint/resume; "
            "use the vmap execution mode"
        )
    if config.local_compute_dtype != "float32":
        # The bf16 + stochastic-rounding local state lives in the vmap
        # engine; running threaded in f32 while the config asks for bf16
        # would silently break the oracle's same-semantics claim.
        raise ValueError(
            "threaded execution mode does not support local_compute_dtype="
            f"{config.local_compute_dtype!r}; use the vmap execution mode"
        )
    if config.lr_schedule.lower() != "constant":
        # The schedule factor is threaded through the vmap round program;
        # the thread-per-client loop would silently train at constant lr.
        raise ValueError(
            "threaded execution mode does not support lr_schedule="
            f"{config.lr_schedule!r}; use the vmap execution mode"
        )
    if config.client_eval is True:
        # The per-client pre-aggregation telemetry is produced by the vmap
        # path's stacked client params; silently running without it would
        # drop promised metrics.
        raise ValueError(
            "threaded execution mode does not support client_eval=True; "
            "use the vmap execution mode"
        )
    if getattr(config, "async_mode", "off").lower() == "on":
        # The thread-per-client oracle reproduces the reference's blocking
        # rendezvous barrier — the exact architecture deadline rounds and
        # the staleness buffer (robustness/arrivals.py) replace; running
        # it synchronously would silently ignore the requested semantics.
        raise ValueError(
            "threaded execution mode does not support async_mode='on'; "
            "use the vmap execution mode"
        )
    if (
        config.client_eval is None
        and algo_name == "fed_quant"
        and config.cohort_size() <= 32
    ):
        # vmap fed_quant auto-enables per-client eval at this cohort size;
        # announce the degradation instead of silently omitting telemetry
        # the other execution mode would have produced.
        get_logger().info(
            "threaded mode does not produce client_eval telemetry (the "
            "vmap execution mode auto-enables it for fed_quant at cohort "
            "size %d)", config.cohort_size(),
        )
    if config.multihost:
        # Enforced at every entry point, not only run_simulation's dispatch:
        # a direct programmatic call would otherwise run one full independent
        # simulation PER process — the silent split the multihost contract
        # forbids.
        raise ValueError(
            "execution_mode='threaded' does not support multihost; "
            "use the vmap execution mode"
        )
    from distributed_learning_simulator_tpu.utils.logging import (
        set_level,
        set_run_artifacts,
    )

    set_level(config.log_level)
    metrics_path = None
    log_dir = None
    if setup_logging:
        # Same per-run artifact contract as the vmap path: a log file under
        # log/<algo>/<dataset>/<model>/ plus metrics.jsonl next to it.
        log_path, log_dir = set_run_artifacts(
            config.log_root, config.distributed_algorithm,
            config.dataset_name, config.model_name,
        )
        metrics_path = os.path.join(log_dir, "metrics.jsonl")
        get_logger().info("log file: %s", log_path)
    if config.profile_dir:
        get_logger().warning(
            "threaded execution mode ignores profile_dir (vmap round loop "
            "only)"
        )
    if dataset is None:
        dataset = get_dataset(
            config.dataset_name, data_dir=config.data_dir, seed=config.seed,
            n_train=config.n_train, n_test=config.n_test,
            **config.dataset_args,
        )
    if client_data is None:
        client_data = build_client_data(config, dataset)

    model = get_model(
        config.model_name, num_classes=dataset.num_classes,
        **config.model_args,
    )
    params = init_params(model, dataset.x_train[:1], seed=config.seed)
    optimizer = make_optimizer(
        config.optimizer_name, config.learning_rate,
        momentum=config.momentum, weight_decay=config.weight_decay,
    )
    from distributed_learning_simulator_tpu.ops.augment import get_augment

    decoder = (
        make_decoder(client_data.sample_shape) if client_data.compact else None
    )
    evaluate = jax.jit(make_eval_fn(model.apply))
    eval_batches = tuple(
        jnp.asarray(a)
        for a in pad_eval_set(
            dataset.x_test, dataset.y_test, config.eval_batch_size
        )
    )

    # Run-scoped recompile counter (docs/OBSERVABILITY.md): worker threads
    # share ONE jitted local_train, so a healthy run compiles each program
    # once total; per-round attribution is meaningless here (threads
    # compile concurrently), so the count is reported once at the end.
    recompile = (
        RecompileMonitor().start()
        if config.telemetry_level.lower() != "off" else None
    )
    t_start = time.perf_counter()
    if algo_name == "sign_SGD":
        server, make_worker = _build_sign_sgd(
            config, model, params, evaluate, eval_batches, decoder,
            client_data, metrics_path,
        )
    else:
        param_transform = None
        if algo_name == "fed_quant" and getattr(config, "qat", True):
            # QAT: straight-through fake-quant on params inside the loss —
            # the same transform the vmap FedQuant installs
            # (algorithms/fed_quant.py client_param_transform).
            from distributed_learning_simulator_tpu.ops.quantize import (
                fake_quant_tree,
            )

            levels = getattr(config, "quant_levels", 256)
            param_transform = lambda p: fake_quant_tree(p, levels)  # noqa: E731
        local_train = jax.jit(
            make_local_train_fn(
                model.apply, optimizer, local_epochs=config.epoch,
                batch_size=config.batch_size, reset_optimizer=True,
                preprocess=decoder,
                augment=get_augment(config.augment),
                param_transform=param_transform,
            )
        )
        if algo_name == "fed_quant":
            server = ThreadedFedQuantServer(config, evaluate, eval_batches,
                                            params, metrics_path=metrics_path)
            q_levels = getattr(config, "quant_levels", 256)

            def make_worker(worker_id, shard):
                return ThreadedFedQuantWorker(
                    worker_id, server.worker_data_queue,
                    server.result_queues[worker_id], local_train, shard,
                    config.round, config.seed, levels=q_levels,
                )
        elif algo_name in ("multiround_shapley_value", "GTG_shapley_value"):
            # Shapley = FedAvg training + server-side contribution scoring:
            # plain FedAvg workers; the scoring reuses the vmap path's
            # strategy objects through the _post_round hook.
            from distributed_learning_simulator_tpu.factory import (
                get_algorithm,
            )

            shapley = get_algorithm(algo_name, config)
            # Count-dependent feasibility (exact Shapley's 2^N bound,
            # GTG's permutation cap) against the TRUE client count,
            # BEFORE any threads spawn (ADVICE r3 up-front-failure rule,
            # relocated from the constructor which only sees
            # worker_number — ADVICE r4).
            shapley.check_cohort(client_data.n_clients)
            shapley.prepare(model.apply, make_eval_fn(model.apply))
            server = ThreadedShapleyServer(
                config, evaluate, eval_batches, params, shapley,
                log_dir=log_dir, metrics_path=metrics_path,
            )

            def make_worker(worker_id, shard):
                return ThreadedWorker(
                    worker_id, server.worker_data_queue,
                    server.result_queues[worker_id], local_train, shard,
                    config.round, config.seed,
                )
        else:
            server = ThreadedServer(config, evaluate, eval_batches, params,
                                    metrics_path=metrics_path)

            def make_worker(worker_id, shard):
                return ThreadedWorker(
                    worker_id, server.worker_data_queue,
                    server.result_queues[worker_id], local_train, shard,
                    config.round, config.seed,
                )

    pool = NativeThreadPool(config.worker_number)
    try:
        for worker_id in range(client_data.n_clients):
            shard = (
                jnp.asarray(client_data.x[worker_id]),
                jnp.asarray(client_data.y[worker_id]),
                jnp.asarray(client_data.mask[worker_id]),
                float(client_data.sizes[worker_id]),
            )
            pool.exec(make_worker(worker_id, shard).train)
        # Error-aware wait instead of a blocking join: if one worker dies,
        # the barrier can never fill and its peers block forever in
        # get_result — a plain join_pending would deadlock. On the first
        # error, stop the server queues (unblocking the waiters with
        # "queue is stopped"), THEN join; pool.results() re-raises the
        # original error (errors are recorded in arrival order).
        while True:
            done, submitted, failed = pool.poll()
            if failed or done == submitted:
                break
            time.sleep(0.02)
        if failed:
            server.stop()
        pool.join_pending()
        if server.server_error is not None:
            # A server-callback failure (eval OOM, full disk) tore the
            # rendezvous down; the workers' queue-stopped errors are
            # symptoms — surface the root cause.
            raise server.server_error
        pool.results()  # re-raise any worker error
    finally:
        # Server first: pool.stop() joins pending work, and any worker
        # still blocked in get_result only unblocks once the queues stop.
        server.stop()
        pool.stop()
        if recompile is not None:
            recompile.stop()
    if server.server_error is not None:
        # The FINAL round's aggregation/eval runs on the serve thread after
        # every worker has already exited (workers end on add_task, not a
        # blocking read), so a failure there surfaces only once
        # server.stop() has joined the serve thread — i.e. here, after the
        # finally. Without this re-check the run would return "success"
        # with the last round's record silently missing.
        raise server.server_error
    total = time.perf_counter() - t_start
    xla_compiles = None
    if recompile is not None:
        events = recompile.drain()
        xla_compiles = len(events)
        get_logger().info(
            "threaded run: %d XLA compile(s) total: %s",
            xla_compiles,
            ", ".join(sorted({name for name, _ in events})) or "-",
        )
    history = server.history
    n = client_data.n_clients
    final_params = (
        server.params if algo_name == "sign_SGD" else server.prev_model
    )
    return {
        "global_params": final_params,
        "history": history,
        "final_accuracy": history[-1]["test_accuracy"] if history else None,
        "total_seconds": total,
        "client_rounds_per_sec": config.round * n / max(total, 1e-9),
        "telemetry_level": config.telemetry_level.lower(),
        "xla_compiles": xla_compiles,
        # Same contract as the vmap path: total detector flags over the
        # run, None when client_stats is off. (The sign_SGD server
        # computes no per-client stats, so its total is simply 0.)
        "clients_flagged": (
            getattr(server, "clients_flagged", 0)
            if ClientStats.from_config(config) is not None else None
        ),
    }


def _build_sign_sgd(config, model, params, evaluate, eval_batches, decoder,
                    client_data, metrics_path):
    """Shared jitted step helpers + server/worker factory for the per-step
    sign-vote mode. The step math comes from the ops/sign.py leaf formulas
    — the single source shared with the vmap SignSGD (the two modes are a
    differential oracle pair); apply is the same jitted closure on server
    and workers so their param replicas stay in bitwise lockstep."""
    from distributed_learning_simulator_tpu.ops.sign import (
        direction_leaf,
        momentum_leaf,
        sign_compress,
        vote_apply_leaf,
    )
    from distributed_learning_simulator_tpu.parallel.engine import make_loss_fn

    lr = config.learning_rate
    mu = config.momentum
    dampening = config.dampening
    nesterov = config.nesterov
    wd = config.weight_decay
    loss_fn = make_loss_fn(model.apply)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    @jax.jit
    def direction_fn(p, momenta, is_first, bx, by, bm):
        if decoder is not None:
            bx = decoder(bx)
        (_, _), grads = grad_fn(p, bx, by, bm)
        momenta_new = jax.tree_util.tree_map(
            lambda m, g: momentum_leaf(m, g, is_first, mu, dampening),
            momenta, grads,
        )
        direction = jax.tree_util.tree_map(
            lambda g, m: direction_leaf(g, m, mu, nesterov),
            grads, momenta_new,
        )
        return sign_compress(direction), momenta_new

    @jax.jit
    def apply_vote(p, voted):
        return jax.tree_util.tree_map(
            lambda pp, vv: vote_apply_leaf(pp, vv, lr, wd), p, voted
        )

    shard_size = client_data.x.shape[1]
    steps_per_round = config.epoch * (shard_size // config.batch_size)
    server = ThreadedSignSGDServer(
        config, evaluate, eval_batches, params, apply_vote, steps_per_round,
        metrics_path=metrics_path,
    )

    def make_worker(worker_id, shard):
        return ThreadedSignSGDWorker(
            worker_id, server.worker_data_queue,
            server.result_queues[worker_id], direction_fn, apply_vote,
            shard, params, config.round, config.epoch, config.batch_size,
            config.seed,
        )

    return server, make_worker
