"""Experiment configuration + CLI.

Parity with the reference's config surface (config.py:9-18 adds
``--distributed_algorithm --worker_number --round`` on top of the external
``DefaultConfig``'s ``--dataset_name --model_name --epoch --learning_rate
--optimizer_name --log_level`` — observed at simulator.sh:1-2), plus the
knobs this framework adds natively: partitioning (IID / Dirichlet), mesh
size, quantization levels, Shapley hyperparameters, checkpointing.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Any

# Valid telemetry_level values (semantics: telemetry/ and
# docs/OBSERVABILITY.md). Defined here — not in the telemetry package —
# so validate() stays import-light (telemetry's submodules import jax);
# the package re-exports it.
TELEMETRY_LEVELS = ("off", "basic", "detailed")

# Valid client_stats values (telemetry/client_stats.py). Same
# import-light placement rationale as TELEMETRY_LEVELS.
CLIENT_STATS_LEVELS = ("off", "on")

# Valid participation_sampler values (ops/sampling.py, which re-exports
# this as SAMPLERS). Same import-light placement rationale as
# TELEMETRY_LEVELS — ops.sampling imports jax.
PARTICIPATION_SAMPLERS = ("exact", "hashed")

# Valid sweep_strategy values (sweep/spec.py re-exports this). Same
# import-light placement rationale as TELEMETRY_LEVELS — the sweep
# engine imports jax.
SWEEP_STRATEGIES = ("auto", "vmapped", "scheduled")

# Registry names of the Shapley servers — the one copy config.validate()
# and sweep/spec.py both refuse sweeps against (their post_round drives
# data-dependent subset evaluation no shared program can serve).
SHAPLEY_ALGORITHMS = ("multiround_shapley_value", "GTG_shapley_value")

# Valid population values (robustness/population.py). Same import-light
# placement rationale as TELEMETRY_LEVELS — the population module pulls
# in the sampler implementations.
POPULATION_MODES = ("static", "dynamic")


@dataclass
class ExperimentConfig:
    # --- reference-parity flags (config.py:16-18, simulator.sh:1-2) --------
    dataset_name: str = "mnist"
    model_name: str = "lenet5"
    distributed_algorithm: str = "fed"
    worker_number: int = 4
    round: int = 10
    epoch: int = 2  # local epochs per round
    learning_rate: float = 0.01
    optimizer_name: str = "SGD"
    log_level: str = "INFO"
    dataset_args: dict[str, Any] = field(default_factory=dict)
    # Extra keyword arguments forwarded to the model constructor
    # (models/registry.py get_model), e.g. {"fold_stage1": false} to disable
    # the W-folded stage-1 layout on resnet18/34 — required to resume
    # checkpoints written by pre-fold builds (the fold changes the parameter
    # TREE STRUCTURE, so resume's structure check rejects mixed configs).
    # CLI: --model_args '{"fold_stage1": false}' (JSON object).
    model_args: dict[str, Any] = field(default_factory=dict)

    # --- training ----------------------------------------------------------
    batch_size: int = 32
    momentum: float = 0.0
    weight_decay: float = 0.0
    dampening: float = 0.0
    nesterov: bool = False
    seed: int = 0
    reset_client_optimizer: bool = True
    # Dtype of the per-client DIVERGED params/grads/momenta during a local
    # run (FedAvg family). "bfloat16" halves the round's dominant HBM
    # traffic at large-model scale (per-client state is ~3x param bytes per
    # in-flight client); the f32 global model remains the broadcast source
    # every round, aggregation accumulates in f32, and every bf16 cast and
    # param store uses hash-dither stochastic rounding with a per-client
    # salt (engine._sr_to_bf16 — plain round-to-nearest measurably stalls
    # long-horizon training; docs/PERFORMANCE.md). Requires
    # reset_client_optimizer=True (persistent f32 optimizer state would
    # mix dtypes across rounds). Worth it for large models (ResNet-18:
    # +9% round rate at f32-parity accuracy); off by default.
    local_compute_dtype: str = "float32"
    # In-step data augmentation (ops/augment.py): "none" or "cifar"
    # (random flip + pad-4 random crop). Replaces the reference's external
    # dataset-transform hook (transform_dataset, SURVEY §2.4) with a pure
    # batched op fused into the round program. FedAvg-family only.
    augment: str = "none"
    # FedAvg aggregation rule (ops/aggregate.py): "mean" (dataset-size-
    # weighted, the reference's only rule), or the Byzantine-robust
    # "median" / "trimmed_mean" (drop trim_ratio of extremes per
    # coordinate) / "krum" (pick the client update nearest its neighbors;
    # trim_ratio doubles as the assumed Byzantine fraction). Robust rules
    # materialize the full per-client parameter stack, so large models cap
    # the feasible client count.
    aggregation: str = "mean"
    trim_ratio: float = 0.1
    # --- failure model (robustness/faults.py; docs/ROBUSTNESS.md) ----------
    # Per-round client fault injection drawn inside the jitted round from
    # the round key: "none" | "dropout" (never trains; excluded + state
    # frozen) | "straggler" (trains but upload arrives late; excluded) |
    # "corrupt_nan" (uploads all-NaN params at full weight) |
    # "corrupt_scale" (uploads its update scaled 100x — finite Byzantine
    # garbage). FedAvg-family and sign_SGD (dropout/straggler only; a 1-bit
    # vote has no parameter-space garbage to inject); the Shapley
    # algorithms refuse any failure model (their utility memo assumes a
    # fixed cohort). Composes with participation_fraction: a
    # sampled-but-failed client contributes nothing.
    failure_mode: str = "none"
    failure_prob: float = 0.0
    # Round-correlated outages: with probability `failure_correlation` a
    # client's failure draw is replaced by one draw SHARED across the
    # round's cohort — marginal rate stays failure_prob, failures cluster
    # into bad rounds (1.0 = all-or-nothing rounds).
    failure_correlation: float = 0.0
    # Re-rolls WHICH clients fail without touching cohort sampling,
    # training batches, or payload keys (fold_in-decoupled stream).
    failure_seed: int = 0
    # --- open-world population (robustness/population.py) -------------------
    # "static" (default): the fixed client population every prior build
    # assumed — the exact pre-feature program (bit-identical history,
    # byte-identical records, config_hash unchanged, 0 post-warmup
    # compiles; the established off-gate contract). "dynamic": an
    # open-world population driven by a round-key-chained registration
    # stream — per round, new clients JOIN (``join_rate``; their data
    # shards are drawn over a growing index space), existing clients
    # DEPART (``depart_rate``; departed indices are masked out of the
    # hashed sampler's first-k-distinct stream and never resampled), and
    # a planted cohort DRIFTS (``drift_fraction``/``drift_factor``:
    # graded label-noise ramping in on a schedule). The per-round cohort
    # stays pinned at the STARTUP population's cohort size, so the
    # compiled round program never changes shape while N grows. Requires
    # client_residency='streamed' + participation_sampler='hashed' +
    # participation_fraction < 1 and the FedAvg family (fed, fed_quant);
    # composes with faults/quorum (a round whose survivors fall below
    # min_survivors after mid-round departures is rejected in-program,
    # previous global retained) and single-host mesh; refuses async
    # mode, round batching, valuation audits, the threaded oracle, and
    # the vmapped sweep strategy — each with the blocking cause named
    # (docs/ROBUSTNESS.md § Dynamic populations).
    population: str = "static"
    # Decouples the registration stream from every other round-key
    # consumer (the PR 2/6 fold_in discipline): re-rolling it changes
    # WHO joins/departs without touching cohort sampling, training
    # batches, fault draws, or payload keys.
    population_seed: int = 0
    # Expected joins per round: floor(join_rate) clients join every
    # round, plus one more with probability frac(join_rate) (drawn from
    # the registration stream). Integer rates give a deterministic
    # growth schedule.
    join_rate: float = 0.0
    # Per-round departure probability of each alive client. Departures
    # are capped so the alive population never falls below the pinned
    # cohort size (the sampler must still fill a cohort); a departure
    # that hits a client sampled in the SAME round zeroes its
    # contribution in-program (quorum-visible).
    depart_rate: float = 0.0
    # Fraction of the STARTUP population planted as a drifting-quality
    # cohort: member i's labels are progressively corrupted toward its
    # grade (drift_factor * rank/m of its samples re-labeled uniformly
    # at random), ramping linearly over the run — the engineered ground
    # truth the streaming valuation is measured against.
    drift_fraction: float = 0.0
    # Peak label-corruption fraction of the worst drifting client.
    drift_factor: float = 0.5
    # --- asynchronous federation (robustness/arrivals.py) -------------------
    # "off" (default): every algorithm runs its exact synchronous-round
    # program (the async machinery is never constructed — trace-time
    # gated like failure_mode). "on": deadline rounds with buffered
    # staleness-weighted aggregation — clients beating round_deadline
    # contribute fresh, late uploads land in a device-resident FedBuff-
    # style buffer applied (with a polynomial staleness discount) once
    # async_buffer_size uploads accumulate. FedAvg family only (fed,
    # fed_quant); sign_SGD, the Shapley servers, and the threaded oracle
    # refuse. round_deadline=inf reproduces sync FedAvg bit-for-bit from
    # the compiled async program (tests/test_async.py).
    async_mode: str = "off"
    # Simulated per-client upload latency, drawn per round from the round
    # key via a fold_in-decoupled stream (activating it re-rolls nothing
    # else): "bimodal" = persistent 80/20 fast/slow population x uniform
    # [0.5, 1.5) jitter; "lognormal" = population factor x
    # exp(arrival_sigma * N(0,1)). Required (non-"none") when
    # async_mode='on'.
    arrival_model: str = "none"
    # Share of the population that is persistently slow, and how much
    # slower it is (the 80/20 heterogeneity knob: defaults model 20% of
    # clients at 8x the upload latency).
    arrival_slow_fraction: float = 0.2
    arrival_slow_factor: float = 8.0
    # Spread of the lognormal per-round jitter (lognormal model only).
    arrival_sigma: float = 0.5
    # Re-rolls WHICH clients are slow (and their jitter) without touching
    # cohort sampling, training batches, failure draws, or payload keys.
    arrival_seed: int = 0
    # Simulated-time budget a round waits for uploads (same units as the
    # arrival model's latencies; a fast client's mean latency is ~1.0).
    # inf = wait for everyone — the synchronous degenerate case.
    round_deadline: float = float("inf")
    # FedBuff K-of-N trigger: the staleness buffer's accumulated late
    # uploads are applied once their count reaches this.
    async_buffer_size: int = 8
    # Exponent of the polynomial staleness discount (1 + s)^(-alpha)
    # weighting a late upload s rounds after its round closed. 0 = full
    # weight regardless of staleness.
    staleness_alpha: float = 0.5
    # Quorum policy (host loop + round program): a round whose survivor
    # count falls below min_survivors — or whose aggregate is non-finite —
    # is REJECTED in-program: the previous global model is retained, and
    # rounds_rejected / survivor_count land in the metrics record and
    # result dict. 0 disables the survivor floor (the non-finite guard
    # still engages whenever a failure model is active).
    min_survivors: int = 0
    # --- server optimizer (FedOpt family; exceeds the reference) -----------
    # "none" = plain FedAvg (the reference's fixed behavior: the aggregate IS
    # the new global model). "sgd"/"adam" treat (prev_global - aggregate) as
    # a pseudo-gradient and apply a server-side optimizer step: FedAvgM with
    # sgd+momentum, FedAdam with adam (Reddi et al., "Adaptive Federated
    # Optimization"). sgd with lr=1.0 and momentum=0 is exactly FedAvg.
    server_optimizer_name: str = "none"
    server_learning_rate: float = 1.0
    server_momentum: float = 0.0

    # --- data partitioning (data/partition.py) -----------------------------
    partition: str = "iid"  # iid | dirichlet
    dirichlet_alpha: float = 0.1
    # Cap on the packed per-client shard size. Every client scans
    # max-shard-size batches per epoch (fixed shapes), so one giant client
    # under extreme Dirichlet skew multiplies EVERY client's step count;
    # capping truncates outlier shards (their extra samples are dropped).
    # None = no cap.
    max_shard_size: int | None = None
    n_train: int | None = None  # subsample for fast runs/tests
    n_test: int | None = None
    data_dir: str | None = None

    # --- quantization (algorithms/fed_quant.py) ----------------------------
    quant_levels: int = 256
    qat: bool = True
    # Per-round per-client local evaluation (FedAvg family: fed,
    # fed_quant): every client's uploaded model is evaluated on the test
    # set BEFORE aggregation, with the post-aggregation global accuracy
    # logged alongside — parity with reference
    # workers/fed_quant_worker.py:55-69. Requires materializing the
    # per-client parameter stack (the fused memory-bounded aggregation
    # path can't serve it), so None = auto: on for fed_quant at cohorts
    # <= 32 (the reference ran 4-8 workers), off otherwise, preserving the
    # large-cohort memory envelope. Explicit True forces it on (fed too);
    # False disables; True with other algorithms is rejected.
    client_eval: bool | None = None

    # --- learning-rate schedule (FedAvg family) -----------------------------
    # Client optimizers reset every round, so the schedule sets each ROUND's
    # effective lr: "constant" | "cosine" (decay to lr_min_factor x lr over
    # lr_schedule_rounds, default the whole run) | "step" (multiply by
    # lr_step_gamma every lr_step_size rounds). Exceeds the reference (its
    # lr is fixed for the whole run, simulator.sh:1); added because
    # constant-lr runs at flagship scale stall or pass through transient
    # collapses (docs/PERFORMANCE.md).
    lr_schedule: str = "constant"
    lr_schedule_rounds: int | None = None  # horizon; None = config.round
    lr_min_factor: float = 0.0
    lr_step_size: int = 30
    lr_step_gamma: float = 0.1

    # --- Shapley (algorithms/shapley.py) ------------------------------------
    round_trunc_threshold: float | None = None
    gtg_eps: float = 1e-3
    gtg_last_k: int = 10
    gtg_converge_criteria: float = 0.05
    # Cap on GTG permutations per round. None = auto ``max(500, 2N)`` at
    # the actual client count N: one GTG sampling iteration draws N
    # permutations (one starting with each worker,
    # GTG_shapley_value_server.py:42-49) and the convergence test needs
    # more than ``max(30, N)`` marginal records, so any cap below 2N can
    # never run a converged estimate — an explicit cap below N is
    # rejected at round-fn build (GTGShapley.check_cohort).
    gtg_max_permutations: int | None = None
    # Cap on test samples used for SUBSET-utility evaluations (the round's
    # reported test metric always uses the full set). None = full set (the
    # reference's behavior). At large N the GTG round is compute-bound on
    # subset inference (tens of thousands of subset models x the whole test
    # set per round); Monte-Carlo SV noise dwarfs eval-subsampling noise,
    # so a few-thousand-sample cap buys a near-linear round-time cut.
    shapley_eval_samples: int | None = None
    # Subset models evaluated per batched XLA call by the Shapley subset
    # evaluator. Each call re-reads the full [n_clients, params] stack for
    # its weighted means, so at large N a larger chunk amortizes that read
    # across more subsets (N=1000 cnn_tpu: the stack is 1.8 GB); the
    # ceiling is activation memory (chunk models x eval-batch activations
    # resident at once).
    shapley_eval_chunk: int = 16
    # Dtype the subset evaluator reads the client-params stack in.
    # "auto" (default) resolves per algorithm (ADVICE r5): "float32" for
    # multiround_shapley_value — the documented exact-parity path, with no
    # Monte-Carlo noise to hide bf16 rounding in — and "bfloat16" for
    # GTG_shapley_value, where halving the per-call stack read (the
    # dominant HBM traffic of a large-N round) is measured fidelity-free.
    # Either aggregation path still ACCUMULATES in f32 (tensordot
    # preferred_element_type / f32 cumulative sums) and the produced
    # subset model is f32. Utilities feed an argmax accuracy, so the
    # measured GTG SV perturbation vs "float32" is below Monte-Carlo noise
    # (tests/test_shapley.py::test_shapley_eval_dtype_agreement). An
    # explicit "float32"/"bfloat16" wins for both algorithms.
    shapley_eval_dtype: str = "auto"
    # How GTG materializes a permutation's prefix models
    # (algorithms/shapley.py): "cumsum" (default) gathers each
    # permutation's clients once in walk order and takes every prefix
    # aggregate from one streamed weighted cumulative sum — O(P) HBM bytes
    # per evaluated prefix instead of the masked path's O(N*P/chunk) share
    # of a full client-stack re-read — with the cross-permutation memo and
    # eps-truncation semantics intact (a truncated walk just stops
    # streaming; nothing is recomputed). "masked" keeps the per-prefix
    # mask-weighted reduction as the differential-testing oracle; the two
    # modes draw identical permutations from a fixed seed and agree
    # exactly in f32 (tests/test_shapley.py).
    gtg_prefix_mode: str = "cumsum"

    # --- execution ----------------------------------------------------------
    # "vmap": the fast path — one jitted round program over the client axis.
    # "threaded": thread-per-client over the native C++ queue/pool runtime
    # (the reference's architecture, servers/server.py + simulator.py:60-69;
    # FedAvg only). Semantically equivalent, ~orders slower; exists for
    # architecture parity and as a differential-testing oracle.
    execution_mode: str = "vmap"
    mesh_devices: int | None = None  # None = single-device vmap path
    # Multi-host (DCN): initialize jax.distributed before device discovery so
    # jax.devices() spans every host's chips and the same mesh/sharding code
    # runs the client axis over ICI within a slice and DCN across slices.
    # Replaces the reference's dormant multi-process path
    # (servers/server.py:11-13, hard-disabled at simulator.py:56). With only
    # --multihost set, relies on the Cloud TPU pod auto-configuration; the
    # explicit coordinator flags cover CPU/GPU clusters and tests.
    multihost: bool = False
    coordinator_address: str | None = None
    num_processes: int | None = None
    process_id: int | None = None
    # Max clients trained concurrently inside one round program. None = all
    # at once (pure vmap). At large N the per-client params/grads/momentum
    # copies and activations exceed HBM; chunking runs vmap-ed chunks
    # sequentially (lax.map) with identical semantics. 0 = auto: computed
    # at startup from the same per-client footprint model the OOM
    # diagnostics use (~4x f32 param bytes per in-flight client, 60% of
    # per-device HBM x mesh size), clamped to the cohort.
    client_chunk_size: int | None = None
    # Size-aware work scheduling for heterogeneous (Dirichlet) shards on the
    # fused FedAvg path: clients are sorted by sample count and grouped into
    # chunks whose scan length matches the chunk's LARGEST member, instead
    # of every client scanning the padded global maximum. Same per-epoch
    # sample coverage (each real sample still visited exactly once per
    # epoch); batch composition — hence the exact SGD trajectory — differs
    # the way any reshuffle does. Per-client OPTIMIZER STEP COUNTS also
    # change: skipped masked-slot steps were real (zero-grad) steps, so
    # with weight_decay > 0 or reset_client_optimizer=False results differ
    # beyond reshuffle noise — matching the reference's per-worker loops
    # (each worker steps only over its own data); set False for
    # bit-comparability with the unscheduled path under those settings
    # (see algorithms/fedavg.py). Skipped automatically when it cannot help
    # (uniform shards) or cannot apply (mesh/multihost sharding, client
    # sampling, materializing algorithms, unchunked rounds).
    bucket_client_work: bool = True
    # Where per-client arrays (data shards + persistent algorithm state)
    # live between rounds. "resident" (default): the full [n_clients, ...]
    # stacks are device-resident for the whole run — the exact
    # pre-feature program, trace-time gated like failure_mode/async_mode.
    # "streamed": the full-N arrays live in a host-side shard store
    # (data/residency.py) and only the sampled cohort's slice is uploaded
    # per dispatch, with the NEXT dispatch's cohort prefetched while the
    # current one computes (parallel/streaming.py) — device memory sizes
    # by the cohort, not the population, which is what lets
    # million-client populations run on one host
    # (docs/PERFORMANCE.md § Streamed client state). Bit-identical to
    # 'resident' at any N: the cohort index sequence is host-replayed
    # from the round-key chain, so sampling/fault/training draws are
    # unchanged. vmap execution only; single-host mesh sharding
    # COMPOSES (the streamer uploads the cohort slice straight into the
    # client-axis PartitionSpec layout — the cohort must divide
    # mesh_devices), and so does MULTIHOST (the distributed shard
    # store: each process owns an N/num_hosts client slice and serves
    # its own members of every round's owner-permuted cohort straight
    # into its addressable shards of the client-axis PartitionSpec —
    # data/residency.py + parallel/streaming.py; needs a mesh spanning
    # every process and the hashed sampler for sampled cohorts, with
    # the remaining composition refusals cause-named in validate() and
    # docs/ROBUSTNESS.md). Refuses algorithms that don't opt in
    # (Algorithm.supports_streamed_residency — the Shapley family's
    # subset re-evaluation assumes a resident stack).
    client_residency: str = "resident"
    # Fraction of clients sampled (without replacement) to train+aggregate
    # each round (FedAvg-family). 1.0 = all clients, the reference's fixed
    # behavior; <1.0 is standard FL client sampling — and unlike the
    # reference's barrier (fed_server.py:75-77, which hangs forever if a
    # client goes missing), non-participants simply sit the round out.
    participation_fraction: float = 1.0
    # HOW the cohort is drawn from the round key (ops/sampling.py).
    # "exact" (default): the bit-identical pre-feature
    # jax.random.choice(replace=False) — a full O(N log N) permutation
    # per round, ~1 s at N=1e6 on a CPU host, which is what left the
    # streamed-residency stream leg host-bound. "hashed": an O(cohort)
    # counter-based Threefry draw (first-k-distinct of a keyed hash
    # stream, duplicates rejected in a fixed small over-draw buffer —
    # no full-N permutation or memory anywhere, numpy-mirrored on the
    # streamed host-replay path). A NEW sampling mode, deliberately not
    # bit-identical to 'exact' (gated and documented like
    # client_residency), but uniform, duplicate-free, deterministic
    # from the round-key chain, and identical between the in-program
    # draw and the host replay by construction. A program-defining knob:
    # 'hashed' lands in config_hash; 'exact' keeps pre-feature hashes
    # (docs/PERFORMANCE.md § Streamed client state has the guidance).
    participation_sampler: str = "exact"
    # Defer each round's metric fetch + post_round by one round so the
    # device->host transfer latency overlaps the next round's compute
    # (significant when the chip sits behind a high-latency link). Auto-
    # disabled for algorithms whose post_round needs same-round metrics
    # (Shapley) and when per-client state must be checkpointed.
    pipeline_rounds: bool = True
    # Fuse this many federated rounds — train + server-optimizer step +
    # server eval + the per-round RNG split chain — into ONE jitted
    # dispatch (parallel/engine.py make_batched_round_fn), with per-round
    # metrics stacked on device and fetched in a single transfer per
    # dispatch. Amortizes the per-round host dispatch/eval-launch/sync
    # overhead the Python round loop cannot hide (~28% of the headline
    # round; docs/PERFORMANCE.md § Round batching). 1 (default) keeps the
    # exact pre-feature per-round dispatch path — trace-time gated like
    # failure_mode/client_stats — and K>1 history is bit-identical to
    # K=1 (the in-program RNG chain replays the host loop's split
    # sequence). Dispatch size is clipped to the next checkpoint
    # boundary, so checkpoint_every and SIGTERM finish-in-flight
    # semantics keep working at batch granularity. Algorithms opt in via
    # Algorithm.supports_round_batching (FedAvg family incl. fed_quant,
    # sign_SGD; the Shapley algorithms refuse — their post_round must see
    # every round). Phase timings/recompile attribution become
    # per-dispatch when K>1 (docs/OBSERVABILITY.md).
    rounds_per_dispatch: int = 1
    # --- telemetry (telemetry/; docs/OBSERVABILITY.md) ----------------------
    # "off" (default): zero instrumentation — metrics.jsonl keeps the
    # legacy v1 record layout byte-for-byte and the measured program is
    # untouched. "basic": per-round phase timings (monotonic clocks around
    # the dispatch sites; JAX dispatch is async, so device time pools into
    # the host_sync phase), XLA recompile counts with offending function
    # names (any compile after the warmup round is flagged as a
    # shape-instability WARNING), and the per-round peak-HBM watermark —
    # recorded under a schema-versioned "telemetry" sub-object in
    # metrics.jsonl. "detailed": same fields, but every phase fences on
    # its output (block_until_ready) so the split is true per-phase device
    # time; fencing defeats round pipelining's transfer/compute overlap —
    # a measurement mode, not a production mode.
    telemetry_level: str = "off"
    # --- distributed tracing (telemetry/spans.py) ---------------------------
    # "off" (default): zero instrumentation — the exact pre-feature
    # program (byte-identical records, 0 post-warmup compiles,
    # config_hash unchanged). "on": a per-host structured span recorder
    # wraps every phase boundary plus the multihost seams (DCN spill
    # exchange wait-vs-transfer, prefetch worker occupancy, checkpoint
    # shard write + manifest barrier wait, recompile events) and journals
    # them to spans_<host_id>.jsonl in the artifacts dir; the buffer
    # doubles as a crash flight recorder (docs/OBSERVABILITY.md
    # § Distributed tracing). Works at any telemetry_level.
    span_trace: str = "off"
    # Journal directory override. None (default): the run's artifacts
    # dir — which only the PRIMARY host has (non-primary hosts skip
    # set_run_artifacts), so multihost runs that want every host's
    # journal pass a shared directory here. Pure I/O routing, never part
    # of the compiled program (config_hash exempt).
    span_dir: str | None = None
    # Bounded in-memory span ring: overflow increments the record's
    # `dropped` counter instead of blocking the hot path.
    span_buffer_size: int = 4096
    # How many completed spans the flight recorder force-flushes (plus
    # every still-open span) on SIGTERM / quorum rejection / crash.
    span_flush_last_k: int = 64
    # --- per-client statistics (telemetry/client_stats.py) ------------------
    # "off" (default): zero instrumentation — the round program is the
    # exact pre-feature program (same RNG streams, same HLO) and
    # metrics.jsonl records stay at schema v2 or below. "on": the round
    # program additionally computes a compact per-client f32 stats vector
    # (loss before/after, update L2 norm, grad norm, cosine against the
    # aggregate delta, non-finite element count) via streaming per-chunk
    # reductions — works on the fused and bucketed aggregation paths
    # without materializing the per-client parameter stack — stacked
    # [N, S] on device; a host-side median/MAD detector flags anomalous
    # clients per round (flagged_clients / flag_reason in the schema-v3
    # metrics record). sign_SGD reports its per-step majority-vote
    # agreement fraction instead (one shared params tree — there is no
    # per-client delta); fed_quant adds the downlink quantization MSE.
    client_stats: str = "off"
    # Fetch cadence: the [N, S] matrix is computed on device every round
    # but transferred to host (inside the round's single metric fetch, so
    # async dispatch is preserved) only on rounds where
    # round_idx % client_stats_every == 0.
    client_stats_every: int = 1
    # Coordinates in the strided per-client delta probe used for the
    # aggregate-cosine statistic (exact when the model has <= this many
    # parameters); norms and non-finite counts are always exact.
    client_stats_probe: int = 4096
    # Robust z-score threshold of the median/MAD detector; lower = more
    # sensitive (see docs/OBSERVABILITY.md § detector tuning).
    client_stats_mad_threshold: float = 8.0
    # --- always-on client valuation (telemetry/valuation.py) ----------------
    # "off" (default): zero instrumentation — the round program is the
    # exact pre-feature program and metrics.jsonl records stay at schema
    # v6 or below. "on" (requires client_stats='on'; FedAvg family, vmap
    # execution): the round additionally emits a per-cohort streaming
    # contribution score (cosine-vs-aggregate x update-norm over the
    # client-stats probe, unit-L1 normalized) that the host scales by the
    # server loss-delta and folds into a persistent exponentially-decayed
    # per-client valuation vector — a cheap always-on Shapley proxy
    # (schema-v7 ``valuation`` sub-object; docs/OBSERVABILITY.md
    # § Client valuation).
    client_valuation: str = "off"
    # Exponential decay of the valuation fold: participants' entries move
    # v <- decay * v + (1 - decay) * loss_delta * score each round.
    # Higher = longer memory.
    valuation_decay: float = 0.9
    # Audit cadence: every this-many rounds (0 = never) the simulator
    # re-materializes the current cohort's exact uploads (round-key
    # replay) and runs a truncated GTG walk over them
    # (algorithms/shapley.gtg_walk), recording Spearman/Pearson
    # correlation between the streaming vector and the exact SVs — the
    # measured fidelity bound on the cheap estimator. Audits are pure
    # reads (training is untouched) and cost roughly one extra cohort
    # training pass + the walk; they refuse failure models, async mode,
    # non-mean aggregation, persistent client optimizers, multihost,
    # and rounds_per_dispatch > 1 (the replay's exactness contract).
    # Single-host mesh_devices > 1 COMPOSES: the audit walk's subset
    # evaluation shards over the mesh, bit-identical to the serial walk
    # (algorithms/shapley.eval_mesh_devices). Caveat, documented not
    # hidden: under mesh the LIVE round's client training is sharded
    # while the replay runs single-placement, so replayed uploads can
    # differ by last-ulp tiling effects — far below the walk's
    # Monte-Carlo noise; the operative contract there is the measured
    # Spearman floor (pinned under mesh), not byte equality.
    valuation_audit_every: int = 0
    # Permutation budget per audit walk (also the number of permutations
    # drawn per truncated sampling iteration). Small-N audits converge
    # within the auto GTG cap; at large N this bounds the walk.
    valuation_audit_permutations: int = 16
    # GTG cross-round subset-utility memo (ROADMAP item 4b): reuse
    # interior subset utilities from the last walk over the SAME cohort
    # (GTG-Shapley's between-round reuse premise: utilities drift slowly
    # once round truncation fires). Off (default) keeps the exact
    # per-round memo semantics; the walk's gtg_memo_hit_rate records how
    # much was reused when on. Realized device savings require
    # gtg_prefix_mode='masked' (its per-subset calls dedup against the
    # seed); under the default 'cumsum' the prefix walker streams every
    # position to keep its carries, so the hit rate measures utility
    # reuse/stability, not work avoided (algorithms/shapley.SubsetMemo).
    # Also governs whether valuation audits seed from the previous audit
    # of the same cohort.
    gtg_cross_round_memo: bool = False
    # Write a jax.profiler trace of the whole run into this directory.
    profile_dir: str | None = None
    # First round the profile trace covers (earlier rounds run untraced).
    # Tracing from round 0 includes the XLA compile, whose host events can
    # flood the profiler buffer and silently drop device events on
    # tunneled chips (simulator.py run loop); bench.py's flagship proxy
    # traces from round 1.
    profile_from_round: int = 0
    # --- predictive cost model (telemetry/costmodel.py) ---------------------
    # Path to an EXISTING jax.profiler trace directory of this program
    # (a previous run's profile_dir; bench.py's proxy uses its own traced
    # run in-process). When set, the categorized op ledger
    # (utils/tracing.categorize_ops) is evaluated through the roofline
    # model against the checked-in topology table and the run's LAST
    # metrics record carries the schema-v6 ``costmodel`` sub-object —
    # predicted per-round time per topology, bottleneck attribution, and
    # model_error_ratio against this run's measured steady round time
    # (docs/OBSERVABILITY.md § Cost model). None (default): records stay
    # at schema v5 or below byte-for-byte. Pure host-side analysis — it
    # never touches the compiled program, so all three knobs are
    # excluded from config_hash like profile_dir.
    cost_model_trace: str | None = None
    # Rounds the reference trace covers (bench.py's cnn proxy traces 3
    # rounds, its flagship proxy 1): ledger totals are divided by this
    # to get the per-round basis the prediction uses.
    cost_model_trace_rounds: int = 1
    # Topology-table entry (telemetry/topologies.py) the prediction is
    # anchored on — the hardware this run's measured round time comes
    # from; model_error_ratio is predicted-vs-measured on this entry.
    cost_model_topology: str = "v5e-1"
    # --- multi-experiment sweep (sweep/; docs/PERFORMANCE.md § Sweep) ------
    # Comma-separated seed list: run one experiment per seed as a FLEET
    # sharing this config's dataset/partition (data seed stays this
    # config's `seed`; each point's seed drives model init + the training
    # RNG chain). Where every point agrees on the program-defining knobs
    # (seed/learning_rate may vary), the fleet runs as ONE vmapped jitted
    # program — compile paid once, each point's history bit-identical to
    # a solo run with that seed on the shared data. None (default) = no
    # sweep; `python -m distributed_learning_simulator_tpu` dispatches to
    # sweep.run_sweep when set.
    sweep_seeds: str | None = None
    # JSON list of per-point config overrides, e.g.
    # '[{"learning_rate": 0.05}, {"learning_rate": 0.1}]'. Combined with
    # sweep_seeds, every override runs at every seed (the grid).
    # Heterogeneous overrides (program-defining knobs) route through the
    # compile-cache-aware scheduler: points group by config_hash and run
    # sequentially through one warm program per (seed-normalized)
    # program class, with per-point compile reuse recorded.
    sweep_points: str | None = None
    # "auto" (default): vmapped fleet when every point is
    # fleet-compatible, else the scheduler. "vmapped"/"scheduled" force
    # a strategy ("vmapped" refuses with the blocking feature named).
    sweep_strategy: str = "auto"
    # Sweep-level checkpointing: every completed point persists its
    # result + schema-v8 records here; an interrupted sweep resumes with
    # sweep_resume=True, re-running only the missing points (points are
    # RNG-independent, so the stitched sweep is bit-identical).
    sweep_dir: str | None = None
    sweep_resume: bool = False
    # Persistent XLA compilation cache directory: the round program's
    # ~20-45s first compile is skipped on any later run with the same
    # shapes (including across processes). Disable with None, or from the
    # CLI with --compilation_cache_dir none (normalized in validate()).
    compilation_cache_dir: str | None = ".jax_cache"
    # Store packed client shards as uint8-flattened arrays (4x less HBM,
    # TPU-friendly tiling); batches are decoded on the fly in the step.
    compact_client_data: bool = True
    eval_batch_size: int = 512
    log_root: str = "log"
    checkpoint_dir: str | None = None
    checkpoint_every: int = 0  # rounds; 0 = disabled
    # Retention: keep only the newest N checkpoints (GC after each
    # successful save), so week-long chaos/preemption runs don't fill the
    # disk. None = keep all. Keep >= 2 when integrity matters: resume
    # falls back past a corrupt/truncated latest checkpoint to the newest
    # VALID one (utils/checkpoint.py).
    checkpoint_keep_last: int | None = None
    resume: bool = False

    def cohort_size(self, n_clients: int | None = None) -> int:
        """Participants per round: the single source of the sampling formula
        (used by the round builder, the OOM hint, and krum's feasibility
        check — keep them in lockstep)."""
        n = self.worker_number if n_clients is None else n_clients
        if self.participation_fraction >= 1.0:
            return n
        return max(1, round(self.participation_fraction * n))

    def validate(self) -> "ExperimentConfig":
        if self.worker_number < 1:
            raise ValueError("worker_number must be >= 1")
        if self.round < 1:
            raise ValueError("round must be >= 1")
        if self.partition not in ("iid", "dirichlet"):
            raise ValueError(f"unknown partition {self.partition!r}")
        if not 0.0 < self.participation_fraction <= 1.0:
            raise ValueError("participation_fraction must be in (0, 1]")
        if self.participation_sampler.lower() not in PARTICIPATION_SAMPLERS:
            raise ValueError(
                f"unknown participation_sampler "
                f"{self.participation_sampler!r}; known: "
                + ", ".join(PARTICIPATION_SAMPLERS)
            )
        if self.compilation_cache_dir in ("", "none", "None"):
            self.compilation_cache_dir = None
        if self.sweep_strategy not in SWEEP_STRATEGIES:
            raise ValueError(
                f"unknown sweep_strategy {self.sweep_strategy!r}; known: "
                + ", ".join(SWEEP_STRATEGIES)
            )
        if self.sweep_resume and not self.sweep_dir:
            raise ValueError(
                "sweep_resume=True needs sweep_dir (where the completed "
                "points were persisted)"
            )
        if self.sweep_seeds or self.sweep_points:
            # Sweep-wide refusals (the one authoritative copy; sweep/
            # spec.py re-checks per point because overrides can
            # introduce any of these).
            if self.execution_mode.lower() == "threaded":
                raise ValueError(
                    "execution_mode='threaded' does not support sweeps: "
                    "the thread-per-client oracle owns one OS thread per "
                    "client per experiment and shares no compiled "
                    "program; run threaded points as solo runs"
                )
            if self.distributed_algorithm in SHAPLEY_ALGORITHMS:
                raise ValueError(
                    f"algorithm {self.distributed_algorithm!r} does not "
                    "support sweeps: its post_round drives data-dependent "
                    "subset evaluation that must observe every round "
                    "synchronously; run Shapley configs as solo runs"
                )
            if (
                self.client_residency.lower() == "streamed"
                and self.rounds_per_dispatch > 1
            ):
                raise ValueError(
                    "client_residency='streamed' with rounds_per_dispatch"
                    " > 1 does not compose with sweeps: the scheduler "
                    "cannot host-replay K stacked cohort plans across "
                    "points sharing one streamer; set "
                    "rounds_per_dispatch=1 or client_residency='resident'"
                )
            if self.multihost:
                raise ValueError(
                    "sweeps do not compose with multihost: every process "
                    "would re-run the whole point list; shard the sweep "
                    "across hosts by splitting the point list instead"
                )
        if self.cost_model_trace_rounds < 1:
            raise ValueError("cost_model_trace_rounds must be >= 1")
        from distributed_learning_simulator_tpu.telemetry.topologies import (
            get_topology,
        )

        get_topology(self.cost_model_topology)  # fail fast on typos
        if not isinstance(self.model_args, dict):
            raise ValueError(
                "model_args must be a dict of model-constructor kwargs "
                '(CLI: a JSON object, e.g. \'{"fold_stage1": false}\')'
            )
        from distributed_learning_simulator_tpu.ops.augment import get_augment

        get_augment(self.augment)  # fail fast on unknown augmentation names
        if self.aggregation.lower() not in ("mean", "median", "trimmed_mean",
                                            "krum"):
            raise ValueError(
                f"unknown aggregation {self.aggregation!r}; known: mean, "
                "median, trimmed_mean, krum"
            )
        if not 0.0 <= self.trim_ratio < 0.5:
            raise ValueError("trim_ratio must be in [0, 0.5)")
        if self.aggregation.lower() == "trimmed_mean":
            from distributed_learning_simulator_tpu.ops.aggregate import (
                trim_count,
            )

            cohort = self.cohort_size()
            if trim_count(cohort, self.trim_ratio) < 1:
                raise ValueError(
                    f"trimmed_mean with trim_ratio={self.trim_ratio} and a "
                    f"cohort of {cohort} trims k=0 clients — a plain mean "
                    "with zero robustness (one NaN upload poisons the "
                    "round); raise trim_ratio or the cohort size so "
                    "trim_ratio * cohort >= 1"
                )
        if self.aggregation.lower() == "krum":
            from distributed_learning_simulator_tpu.ops.aggregate import (
                trim_count,
            )

            cohort = self.cohort_size()
            f = trim_count(cohort, self.trim_ratio)
            if cohort < 2 * f + 3:
                raise ValueError(
                    f"krum needs n >= 2f + 3 participants (cohort={cohort}, "
                    f"assumed Byzantine f={f}); lower trim_ratio or raise "
                    "worker_number/participation_fraction"
                )
        from distributed_learning_simulator_tpu.robustness.faults import (
            MODES as _FAILURE_MODES,
        )

        if self.failure_mode not in _FAILURE_MODES:
            raise ValueError(
                f"unknown failure_mode {self.failure_mode!r}; known: "
                + ", ".join(_FAILURE_MODES)
            )
        if not 0.0 <= self.failure_prob <= 1.0:
            raise ValueError("failure_prob must be in [0, 1]")
        if not 0.0 <= self.failure_correlation <= 1.0:
            raise ValueError("failure_correlation must be in [0, 1]")
        if self.min_survivors < 0:
            raise ValueError("min_survivors must be >= 0")
        if self.min_survivors > self.cohort_size():
            raise ValueError(
                f"min_survivors={self.min_survivors} exceeds the sampled "
                f"cohort size ({self.cohort_size()}); every round would be "
                "rejected — lower it or raise worker_number/"
                "participation_fraction"
            )
        _failure_active = (
            self.failure_mode != "none" and self.failure_prob > 0.0
        )
        if _failure_active:
            # (The Shapley algorithms refuse failure injection too, but in
            # ONE place — their constructors via _check_shapley_config —
            # so the refusal can't drift across an algorithm-name list
            # kept here.)
            if self.execution_mode.lower() == "threaded":
                raise ValueError(
                    "the threaded execution oracle does not model client "
                    "failures; use execution_mode='vmap' with a failure "
                    "model"
                )
        from distributed_learning_simulator_tpu.robustness.arrivals import (
            ARRIVAL_MODES as _ARRIVAL_MODES,
            AsyncFederation,
        )

        if self.arrival_model not in _ARRIVAL_MODES:
            # Checked even at async_mode='off' so a typo fails fast
            # instead of surfacing only when async is later turned on.
            raise ValueError(
                f"unknown arrival_model {self.arrival_model!r}; known: "
                + ", ".join(_ARRIVAL_MODES)
            )
        # The ONE authoritative async_mode / arrival-model gate (unknown
        # mode, arrival_model='none' under async) — from_config raises
        # the same errors direct library users see.
        AsyncFederation.from_config(self)
        if self.async_mode.lower() == "on":
            if not self.round_deadline > 0.0:
                raise ValueError("round_deadline must be > 0 (inf = sync)")
            if self.async_buffer_size < 1:
                raise ValueError("async_buffer_size must be >= 1")
            if self.staleness_alpha < 0.0:
                raise ValueError("staleness_alpha must be >= 0")
            if not 0.0 <= self.arrival_slow_fraction <= 1.0:
                raise ValueError(
                    "arrival_slow_fraction must be in [0, 1]"
                )
            if self.arrival_slow_factor < 1.0:
                raise ValueError("arrival_slow_factor must be >= 1")
            if self.arrival_model == "lognormal" and self.arrival_sigma <= 0.0:
                # sigma is the lognormal jitter spread only; a bimodal
                # run must not be refused over a knob it never reads.
                raise ValueError("arrival_sigma must be > 0")
        if self.checkpoint_keep_last is not None and (
            self.checkpoint_keep_last < 1
        ):
            raise ValueError(
                "checkpoint_keep_last must be >= 1 or None (= keep all)"
            )
        if self.local_compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"unknown local_compute_dtype {self.local_compute_dtype!r}; "
                "known: float32, bfloat16"
            )
        if (
            self.local_compute_dtype == "bfloat16"
            and not self.reset_client_optimizer
        ):
            raise ValueError(
                "local_compute_dtype='bfloat16' requires "
                "reset_client_optimizer=True (persistent per-client "
                "optimizer state is f32 and would mix dtypes across rounds)"
            )
        if (
            self.client_eval is True
            and self.distributed_algorithm not in ("fed", "fed_quant")
        ):
            # Reject rather than silently ignore: the telemetry machinery
            # lives in the FedAvg round/post_round pair; the Shapley
            # servers override post_round entirely and sign_SGD keeps one
            # shared params tree (there is no per-client model to score).
            raise ValueError(
                "client_eval=True is only supported for the FedAvg family "
                f"(fed, fed_quant), not {self.distributed_algorithm!r}"
            )
        if self.client_chunk_size is not None and self.client_chunk_size < 0:
            raise ValueError(
                "client_chunk_size must be positive, 0 (auto), or None"
            )
        if self.execution_mode.lower() not in ("vmap", "threaded"):
            raise ValueError(
                f"unknown execution_mode {self.execution_mode!r}; known: "
                "vmap, threaded"
            )
        if self.client_residency.lower() not in ("resident", "streamed"):
            raise ValueError(
                f"unknown client_residency {self.client_residency!r}; "
                "known: resident, streamed"
            )
        if self.client_residency.lower() == "streamed":
            if self.execution_mode.lower() == "threaded":
                raise ValueError(
                    "client_residency='streamed' requires the vmap "
                    "execution mode (the threaded oracle owns its own "
                    "per-worker data)"
                )
            if self.multihost:
                # Streamed x multihost COMPOSES since the distributed
                # shard store landed (data/residency.DistributedShardStore
                # + parallel/streaming.DistributedCohortStreamer): each
                # process owns an N/num_hosts client slice and serves its
                # own cohort members straight into its addressable shards
                # of the client-axis PartitionSpec — only the per-round
                # ownership-imbalance spill (O(sqrt(cohort)) rows) ever
                # crosses DCN. The refinements below are the remaining
                # cause-named refusals (docs/ROBUSTNESS.md composition
                # matrix).
                if self.mesh_devices is None or self.mesh_devices < 2:
                    raise ValueError(
                        "client_residency='streamed' under multihost "
                        "needs mesh_devices set to the GLOBAL device "
                        "count: the distributed shard store serves each "
                        "host's cohort members into its addressable "
                        "shards of the client-axis PartitionSpec, so "
                        "there must be a mesh spanning every process"
                    )
                if (
                    self.participation_fraction < 1.0
                    and self.participation_sampler.lower() != "hashed"
                ):
                    raise ValueError(
                        "client_residency='streamed' under multihost "
                        "requires participation_sampler='hashed' for "
                        "sampled cohorts: every host replays the full "
                        "cohort independently each round, and only the "
                        "O(cohort) hashed draw keeps that replay free "
                        "at million-client populations (the exact "
                        "sampler pays an O(N log N) permutation PER "
                        "HOST per round)"
                    )
                if self.rounds_per_dispatch > 1:
                    raise ValueError(
                        "client_residency='streamed' under multihost "
                        "requires rounds_per_dispatch=1: a fused "
                        "K-round dispatch would need K owner-sharded "
                        "assemblies and spill exchanges inside one "
                        "program, which the host-side exchange cannot "
                        "serve mid-dispatch"
                    )
                if (
                    self.distributed_algorithm == "fed_quant"
                    and self.participation_fraction < 1.0
                ):
                    raise ValueError(
                        "client_residency='streamed' under multihost "
                        "does not compose with fed_quant at sampled "
                        "cohorts: its uplink stochastic-quantization "
                        "keys split per cohort ROW, so the "
                        "owner-permuted layout would dither each "
                        "client's upload with a different key than "
                        "the 1-process run (silently breaking the "
                        "per-client bit-identity contract the "
                        "draw_pos operand provides for training "
                        "draws); use participation_fraction=1, plain "
                        "'fed', or client_residency='resident'"
                    )
                if self.async_mode.lower() == "on":
                    raise ValueError(
                        "client_residency='streamed' under multihost "
                        "does not compose with async_mode='on': the "
                        "staleness buffer's late-upload row has been "
                        "validated on single-host meshes only; use "
                        "client_residency='resident' for async "
                        "multihost runs"
                    )
                if self.client_stats.lower() == "on":
                    raise ValueError(
                        "client_residency='streamed' under multihost "
                        "does not compose with client_stats='on': the "
                        "per-client stats matrix is client-axis sharded "
                        "across processes and the host-side detector "
                        "fetch would need a cross-host gather every "
                        "round; use resident multihost for client stats"
                    )
                if self.client_valuation.lower() == "on":
                    raise ValueError(
                        "client_residency='streamed' under multihost "
                        "does not compose with client_valuation='on': "
                        "the streaming valuation vector is a full-N "
                        "host array with ONE owner, which the "
                        "host-sharded store deliberately no longer has"
                    )
                if self.participation_fraction >= 1.0 and (
                    (
                        self.distributed_algorithm == "sign_SGD"
                        and self.momentum != 0.0
                    )
                    or not self.reset_client_optimizer
                ):
                    raise ValueError(
                        "client_residency='streamed' under multihost "
                        "does not compose with persistent per-client "
                        "state at full participation (momentum "
                        "sign_SGD / reset_client_optimizer=False): the "
                        "full-population state stack stays "
                        "device-resident across rounds, which the "
                        "per-host store cannot checkpoint-own; sampled "
                        "cohorts (participation_fraction < 1) carry "
                        "state through the owner exchange, or use "
                        "client_residency='resident'"
                    )
        if self.population.lower() not in POPULATION_MODES:
            raise ValueError(
                f"unknown population {self.population!r}; known: "
                + ", ".join(POPULATION_MODES)
            )
        if self.join_rate < 0.0:
            raise ValueError("join_rate must be >= 0")
        if not 0.0 <= self.depart_rate < 1.0:
            raise ValueError("depart_rate must be in [0, 1)")
        if not 0.0 <= self.drift_fraction <= 1.0:
            raise ValueError("drift_fraction must be in [0, 1]")
        if not 0.0 <= self.drift_factor <= 1.0:
            raise ValueError("drift_factor must be in [0, 1]")
        if self.population.lower() == "dynamic":
            # Every refusal names the blocking feature (the PR 2/6/7
            # discipline): dynamic populations are an open-world
            # scenario layer, and each composition below is either
            # pinned by a test or refused here with its cause.
            if self.execution_mode.lower() == "threaded":
                raise ValueError(
                    "population='dynamic' requires the vmap execution "
                    "mode: the thread-per-client oracle spawns one OS "
                    "thread per client at startup and cannot register "
                    "or retire clients mid-run"
                )
            if self.distributed_algorithm not in ("fed", "fed_quant"):
                cause = (
                    "its utility memo assumes a fixed cohort over a "
                    "fixed population"
                    if self.distributed_algorithm in SHAPLEY_ALGORITHMS
                    else "its round program does not take the dynamic-"
                         "population departure operand (FedAvg family "
                         "only: fed, fed_quant)"
                )
                raise ValueError(
                    f"algorithm {self.distributed_algorithm!r} does not "
                    f"support population='dynamic': {cause}"
                )
            if self.client_residency.lower() != "streamed":
                raise ValueError(
                    "population='dynamic' requires client_residency="
                    "'streamed': the resident path bakes the population "
                    "length into every device array shape, so each join "
                    "round would recompile the round program; the "
                    "streamed cohort pipeline is population-size-free "
                    "(the host shard store grows by appending)"
                )
            if self.participation_sampler.lower() != "hashed":
                raise ValueError(
                    "population='dynamic' requires participation_sampler"
                    "='hashed': the exact sampler's O(N log N) "
                    "permutation draw has no maskable stream; the hashed "
                    "first-k-distinct stream masks departed indices "
                    "exactly (ops/sampling.py)"
                )
            if self.participation_fraction >= 1.0:
                raise ValueError(
                    "population='dynamic' requires participation_fraction"
                    " < 1: the cohort is pinned at the startup "
                    "population's sampled size so the compiled round "
                    "program never changes shape while N grows; a "
                    "full-participation cohort would have to grow with "
                    "the population"
                )
            if self.rounds_per_dispatch > 1:
                raise ValueError(
                    "population='dynamic' requires rounds_per_dispatch=1:"
                    " registration events (joins/departures/drift) apply "
                    "at host round boundaries, which a fused K-round "
                    "scan dispatch does not expose"
                )
            if self.multihost:
                raise ValueError(
                    "population='dynamic' does not compose with "
                    "multihost: joins grow the store and would "
                    "re-partition the distributed shard store's "
                    "ownership bounds mid-run; run dynamic populations "
                    "on one host's mesh"
                )
            if self.async_mode.lower() == "on":
                raise ValueError(
                    "population='dynamic' does not compose with "
                    "async_mode='on': the persistent per-client arrival "
                    "speed table is built into the round program at "
                    "trace time for the startup population — a joined "
                    "client has no speed row; set async_mode='off'"
                )
            if self.valuation_audit_every > 0:
                raise ValueError(
                    "population='dynamic' does not compose with "
                    "valuation audits: the auditor replays cohorts from "
                    "a startup snapshot of the packed shards, which "
                    "churn (joins and drifting labels) invalidates; set "
                    "valuation_audit_every=0 (the streaming valuation "
                    "itself composes — its vector grows with the "
                    "population)"
                )
        if self.rounds_per_dispatch < 1:
            raise ValueError("rounds_per_dispatch must be >= 1")
        if (
            self.rounds_per_dispatch > 1
            and self.execution_mode.lower() == "threaded"
        ):
            # The thread-per-client oracle sequences rounds on the host by
            # construction; there is no program to batch.
            raise ValueError(
                "rounds_per_dispatch > 1 requires the vmap execution mode "
                "(the threaded oracle dispatches per round)"
            )
        if (
            self.shapley_eval_samples is not None
            and self.shapley_eval_samples < 1
        ):
            raise ValueError("shapley_eval_samples must be >= 1 or None")
        if self.shapley_eval_chunk < 1:
            raise ValueError("shapley_eval_chunk must be >= 1")
        if self.shapley_eval_dtype not in ("auto", "float32", "bfloat16"):
            raise ValueError(
                "shapley_eval_dtype must be 'auto', 'float32' or "
                f"'bfloat16', got {self.shapley_eval_dtype!r}"
            )
        if self.gtg_prefix_mode not in ("cumsum", "masked"):
            raise ValueError(
                "gtg_prefix_mode must be 'cumsum' or 'masked', got "
                f"{self.gtg_prefix_mode!r}"
            )
        if self.telemetry_level.lower() not in TELEMETRY_LEVELS:
            raise ValueError(
                f"unknown telemetry_level {self.telemetry_level!r}; known: "
                + ", ".join(TELEMETRY_LEVELS)
            )
        if self.span_trace.lower() not in ("off", "on"):
            raise ValueError(
                f"unknown span_trace {self.span_trace!r}; known: off, on"
            )
        if self.span_buffer_size < 1:
            raise ValueError("span_buffer_size must be >= 1")
        if self.span_flush_last_k < 1:
            raise ValueError("span_flush_last_k must be >= 1")
        if self.client_stats.lower() not in CLIENT_STATS_LEVELS:
            raise ValueError(
                f"unknown client_stats {self.client_stats!r}; known: "
                + ", ".join(CLIENT_STATS_LEVELS)
            )
        if self.client_stats_every < 1:
            raise ValueError("client_stats_every must be >= 1")
        if self.client_stats_probe < 1:
            raise ValueError("client_stats_probe must be >= 1")
        if self.client_stats_mad_threshold <= 0.0:
            raise ValueError("client_stats_mad_threshold must be > 0")
        if self.client_valuation.lower() not in ("off", "on"):
            raise ValueError(
                f"unknown client_valuation {self.client_valuation!r}; "
                "known: off, on"
            )
        if not 0.0 <= self.valuation_decay < 1.0:
            raise ValueError("valuation_decay must be in [0, 1)")
        if self.valuation_audit_every < 0:
            raise ValueError("valuation_audit_every must be >= 0")
        if self.valuation_audit_permutations < 1:
            raise ValueError("valuation_audit_permutations must be >= 1")
        if self.client_valuation.lower() == "on":
            if self.client_stats.lower() != "on":
                # The streaming scores are DERIVED from the client-stats
                # matrix (telemetry/valuation.py) — valuation without the
                # stats machinery has nothing to score.
                raise ValueError(
                    "client_valuation='on' requires client_stats='on' "
                    "(the streaming scores derive from the per-client "
                    "stats matrix)"
                )
            if self.execution_mode.lower() == "threaded":
                raise ValueError(
                    "client_valuation='on' requires the vmap execution "
                    "mode (the threaded oracle computes no in-round "
                    "score vector)"
                )
            if self.distributed_algorithm == "sign_SGD":
                # sign_SGD keeps one shared params tree — there is no
                # per-client update delta to score.
                raise ValueError(
                    "client_valuation='on' is not supported for sign_SGD "
                    "(no per-client update delta to score)"
                )
        if self.valuation_audit_every > 0:
            # The audit replays the cohort's local training exactly from
            # the round key; every condition below would make the replay
            # (or the subset-utility semantics) diverge from the live
            # round — refuse with the cause, never audit garbage.
            if self.client_valuation.lower() != "on":
                raise ValueError(
                    "valuation_audit_every > 0 requires "
                    "client_valuation='on' (there is no streaming vector "
                    "to audit)"
                )
            if self.distributed_algorithm != "fed":
                # fed_quant is deliberately excluded: the live fused
                # path quantizes uploads with PER-CHUNK payload keys
                # (chunked_accumulate per_chunk / the bucketed group
                # split), which a whole-stack replay cannot reproduce —
                # the audit would score re-quantized uploads the server
                # never saw. The Shapley servers already compute exact
                # SVs; sign_SGD has no per-client delta.
                raise ValueError(
                    "valuation audits support distributed_algorithm="
                    f"'fed' only, not {self.distributed_algorithm!r} "
                    "(fed_quant's per-chunk upload-quantization keys "
                    "cannot be replayed exactly on a whole-stack audit; "
                    "the Shapley servers already compute exact SVs)"
                )
            if self.failure_mode != "none" and self.failure_prob > 0.0:
                raise ValueError(
                    "valuation audits refuse failure injection (the "
                    "cohort replay assumes honest uploads, the same "
                    "contract as Shapley scoring); set failure_mode="
                    "'none' or valuation_audit_every=0"
                )
            if self.async_mode.lower() == "on":
                raise ValueError(
                    "valuation audits refuse async_mode='on' (subset "
                    "utilities assume a synchronous cohort); set "
                    "valuation_audit_every=0"
                )
            if self.aggregation.lower() != "mean":
                raise ValueError(
                    "valuation audits assume the weighted-mean "
                    "aggregator (subset utilities are weighted means); "
                    "set aggregation='mean' or valuation_audit_every=0"
                )
            if not self.reset_client_optimizer:
                raise ValueError(
                    "valuation audits require reset_client_optimizer="
                    "True (the replay cannot reconstruct pre-round "
                    "persistent optimizer state)"
                )
            if self.rounds_per_dispatch > 1:
                raise ValueError(
                    "valuation audits require rounds_per_dispatch=1 "
                    "(the audit replays one round's key chain against "
                    "that round's pre-round global params)"
                )
            if self.multihost:
                # Single-host mesh sharding COMPOSES (the audit walk's
                # subset evaluation partitions over the mesh,
                # algorithms/shapley.eval_mesh_devices — bit-identical
                # to the serial walk); multihost does not: the audit's
                # cohort replay and data-dependent walk are driven by
                # ONE host process.
                raise ValueError(
                    "valuation audits do not compose with multihost: the "
                    "audit's cohort replay and GTG walk are driven by a "
                    "single host process; run audits on one host's mesh "
                    "(single-process mesh_devices sharding is supported)"
                )
        if self.profile_from_round < 0:
            raise ValueError(
                f"profile_from_round must be >= 0, got "
                f"{self.profile_from_round}"
            )
        if (
            self.gtg_max_permutations is not None
            and self.gtg_max_permutations < 1
        ):
            raise ValueError(
                "gtg_max_permutations must be >= 1 or None (= auto "
                "max(500, 2N))"
            )
        if self.lr_schedule.lower() not in ("constant", "cosine", "step"):
            raise ValueError(
                f"unknown lr_schedule {self.lr_schedule!r}; known: "
                "constant, cosine, step"
            )
        if self.lr_schedule.lower() != "constant":
            if self.distributed_algorithm == "sign_SGD":
                # sign_SGD's lr lives in the vote-apply (torch-SGD parity
                # semantics); a round schedule there is untested territory —
                # reject rather than silently ignore.
                raise ValueError(
                    "lr_schedule is supported for the FedAvg family only, "
                    "not sign_SGD"
                )
            if not 0.0 <= self.lr_min_factor <= 1.0:
                raise ValueError("lr_min_factor must be in [0, 1]")
            if (
                self.lr_schedule_rounds is not None
                and self.lr_schedule_rounds < 1
            ):
                raise ValueError(
                    "lr_schedule_rounds must be >= 1 or None (= whole run)"
                )
            if self.lr_step_size < 1:
                raise ValueError("lr_step_size must be >= 1")
            if not 0.0 <= self.lr_step_gamma <= 1.0:
                raise ValueError("lr_step_gamma must be in [0, 1]")
        server_opt = self.server_optimizer_name.lower()
        if server_opt not in ("none", "", "sgd", "adam"):
            raise ValueError(
                f"unknown server optimizer {self.server_optimizer_name!r}; "
                "known: none, sgd, adam"
            )
        if self.server_learning_rate <= 0.0:
            raise ValueError("server_learning_rate must be > 0")
        if not 0.0 <= self.server_momentum < 1.0:
            raise ValueError("server_momentum must be in [0, 1)")
        if server_opt == "adam" and self.server_momentum:
            raise ValueError(
                "server_momentum is only used by the sgd server optimizer; "
                "adam ignores it — unset one of the two"
            )
        return self


def _add_args(parser: argparse.ArgumentParser) -> None:
    for f in dataclasses.fields(ExperimentConfig):
        if f.name == "dataset_args":
            continue
        arg = f"--{f.name}"
        if f.name == "model_args":
            import json

            parser.add_argument(
                arg, type=json.loads, default={},
                help="JSON object of model-constructor kwargs, e.g. "
                     '\'{"fold_stage1": false}\'',
            )
            continue
        if f.type in ("bool", bool):
            parser.add_argument(arg, type=lambda s: s.lower() in ("1", "true"),
                                default=f.default)
        elif f.name == "client_eval":  # tri-state: auto/None, true, false
            parser.add_argument(
                arg,
                type=lambda s: (
                    None if s.lower() in ("auto", "none")
                    else s.lower() in ("1", "true")
                ),
                default=None,
            )
        elif f.name in ("n_train", "n_test", "mesh_devices", "num_processes",
                        "process_id", "lr_schedule_rounds",
                        "shapley_eval_samples", "gtg_max_permutations",
                        "checkpoint_keep_last"):
            parser.add_argument(arg, type=int, default=None)
        elif f.name in ("round_trunc_threshold", "checkpoint_dir", "data_dir",
                        "profile_dir", "cost_model_trace",
                        "client_chunk_size", "max_shard_size",
                        "coordinator_address", "sweep_seeds",
                        "sweep_points", "sweep_dir", "span_dir"):
            typ = {
                "round_trunc_threshold": float,
                "client_chunk_size": int,
                "max_shard_size": int,
            }.get(f.name, str)
            parser.add_argument(arg, type=typ, default=None)
        else:
            parser.add_argument(arg, type=type(f.default), default=f.default)


def get_config(args: list[str] | None = None) -> ExperimentConfig:
    """Parse CLI args into an ExperimentConfig (reference config.py:22-25)."""
    parser = argparse.ArgumentParser(
        description="TPU-native distributed learning simulator"
    )
    _add_args(parser)
    ns = parser.parse_args(args)
    return ExperimentConfig(**vars(ns)).validate()
