"""Multi-experiment sweep engine (ROADMAP item 1).

``SweepSpec`` is the front door (validation, strategy resolution,
refusals); ``run_sweep`` executes a fleet — vmapped over an experiment
axis where the points allow, config-hash-scheduled through warm
programs where they don't. ``SweepScheduler`` and ``lean_supported``
are the reusable warm-program pieces (bench.py and
scripts/measure_scaling.py route repeated runs through them so warmup
is paid once and recorded explicitly).
"""

from distributed_learning_simulator_tpu.sweep.engine import (
    EXPERIMENT_AXIS,
    SweepScheduler,
    lean_supported,
    run_sweep,
)
from distributed_learning_simulator_tpu.sweep.spec import (
    FLEET_AXES,
    SWEEP_STRATEGIES,
    SweepPoint,
    SweepSpec,
)

__all__ = [
    "EXPERIMENT_AXIS",
    "FLEET_AXES",
    "SWEEP_STRATEGIES",
    "SweepPoint",
    "SweepScheduler",
    "SweepSpec",
    "lean_supported",
    "run_sweep",
]
