"""Sweep execution engine: vmapped seed fleets + a warm-program scheduler.

Two execution strategies behind the :class:`~distributed_learning_simulator_tpu.sweep.spec.SweepSpec`
front door (strategy selection + refusals live there):

* **vmapped fleet** — points that agree on every program-defining knob
  except the fleet axes (seed, learning_rate) stack on a new leading
  experiment axis: per-point model inits and RNG key chains become
  ``[E, ...]`` operands, per-point learning rates a length-E f32 factor
  vector (the PR 5 ``lr_factors`` precedent), and ONE jitted program
  (``parallel/engine.make_experiment_round_fn``) trains every
  experiment per dispatch. Point ``i``'s metric history is bit-identical
  to a solo ``run_simulation`` with that seed on the shared data
  (verified: tests/test_sweep.py) — compile is paid once for the fleet.
  With ``mesh_devices > 1`` the EXPERIMENT axis is sharded over the mesh
  (each device owns E/n whole experiments — sweep points packed across
  chips; cohort shapes are per-experiment, so they always "allow").
  Under a mesh the RNG/cohort streams stay exact but metric values hold
  to reduction-order tolerance — the SPMD partitioner may re-associate
  intra-experiment reductions, the same documented contract as
  resident-vs-mesh fed runs (docs/ROBUSTNESS.md).

* **scheduled** — heterogeneous points group by
  ``utils/reporting.config_hash`` and each group runs sequentially
  through one warm program. Programs are cached under a SEED-NORMALIZED
  program key: the seed is a pure operand (model init + the key chain),
  so seed-varied groups share one compiled program even though their
  config hashes differ — per-point ``compile_reused`` records exactly
  which points rode a warm program. Points whose features the lean
  warm-program loop does not cover (mesh/streamed/async/telemetry/...)
  fall back to a full ``run_simulation`` with ``compile_reused=False``
  — recorded honestly, never silently.

Sweep-level checkpoint/resume: with ``sweep_dir`` set, every completed
point persists its result (``point_NNN.json``) and its per-round
records append to the sweep's ``metrics.jsonl`` (schema v8 ``sweep``
sub-object through the shared builder — utils/reporting.py). A killed
sweep resumes with ``sweep_resume=True``: persisted points load, only
the remainder executes — and because points are independent
(per-experiment RNG chains), the stitched results are bit-identical to
the uninterrupted sweep (tests/test_sweep.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.algorithms.base import RoundContext
from distributed_learning_simulator_tpu.config import SHAPLEY_ALGORITHMS
from distributed_learning_simulator_tpu.data.registry import get_dataset
from distributed_learning_simulator_tpu.factory import get_algorithm
from distributed_learning_simulator_tpu.models.registry import (
    get_model,
    init_params,
)
from distributed_learning_simulator_tpu.parallel.engine import (
    make_decoder,
    make_eval_fn,
    make_experiment_eval_fn,
    make_experiment_round_fn,
    make_optimizer,
    make_reshaper,
    pad_eval_set,
)
from distributed_learning_simulator_tpu.parallel.mesh import (
    make_mesh,
    replicate,
    shard_client_data,
)
from distributed_learning_simulator_tpu.sweep.spec import SweepSpec
from distributed_learning_simulator_tpu.utils.logging import get_logger
from distributed_learning_simulator_tpu.utils.reporting import (
    build_round_record,
    config_hash,
)

#: Chaos hook (the robustness/chaos.py idiom): when set to integer k,
#: run_sweep raises after k newly-executed points have been persisted —
#: the harness tests/test_sweep.py uses to prove sweep-level resume
#: stitches bit-identically. Inert in production.
_CRASH_ENV = "DLS_SWEEP_CRASH_AFTER"

#: Axis name of the experiment mesh (vmapped fleet packing): distinct
#: from the solo simulator's "clients" axis — here each device owns
#: whole experiments, not client shards.
EXPERIMENT_AXIS = "experiments"


def _seed_key(seed: int):
    """The solo round loop's RNG root for ``config.seed`` — one
    definition shared by the fleet's stacked key chain and the lean
    scheduler loop, so every strategy replays ``run_simulation``'s
    ``jax.random.key(config.seed + 1)`` exactly."""
    return jax.random.key(seed + 1)


def _sweep_record(point, strategy: str, compile_reused: bool,
                  experiments: int | None = None) -> dict:
    """The schema-v8 ``sweep`` sub-object for one point's records."""
    rec = {
        "point": point.index,
        "seed": int(point.config.seed),
        "lr": float(point.config.learning_rate),
        "strategy": strategy,
        "group": config_hash(point.config),
        "compile_reused": bool(compile_reused),
    }
    if experiments is not None:
        rec["experiments"] = int(experiments)
    return rec


def _shared_data(base, dataset, client_data):
    """Resolve the sweep's ONE dataset + client partition (the base
    config's data seed — see sweep/spec.py's data contract)."""
    from distributed_learning_simulator_tpu.simulator import (
        build_client_data,
    )

    if dataset is None:
        dataset = get_dataset(
            base.dataset_name, data_dir=base.data_dir, seed=base.seed,
            n_train=base.n_train, n_test=base.n_test, **base.dataset_args,
        )
    if client_data is None:
        client_data = build_client_data(base, dataset)
    return dataset, client_data


class _Program:
    """One compiled round program + everything needed to run points
    through it: the warm unit the scheduler caches and the fleet builds
    once. Data device arrays are owned by the enclosing scheduler/fleet
    (shared across programs — one upload per sweep)."""

    def __init__(self, cfg, dataset, client_data, devices):
        from distributed_learning_simulator_tpu.simulator import (
            _assert_client_stack_feasible,
            _assert_residency_feasible,
            _auto_chunk_size,
        )

        self.model = get_model(
            cfg.model_name, num_classes=dataset.num_classes,
            **cfg.model_args,
        )
        # The init batch is kept so each point re-initializes with ITS
        # seed; proto_params serve shape/feasibility math only.
        self.init_batch = dataset.x_train[:1]
        self.proto_params = init_params(
            self.model, self.init_batch, seed=cfg.seed
        )
        if cfg.client_chunk_size == 0:  # auto, same resolution as solo
            cfg = dataclasses.replace(
                cfg,
                client_chunk_size=_auto_chunk_size(
                    cfg, self.proto_params, client_data.n_clients
                ),
            )
        self.cfg = cfg
        self.n_clients = client_data.n_clients
        self.optimizer = make_optimizer(
            cfg.optimizer_name, cfg.learning_rate,
            momentum=cfg.momentum, weight_decay=cfg.weight_decay,
        )
        self.algorithm = get_algorithm(cfg.distributed_algorithm, cfg)
        _assert_residency_feasible(
            cfg, self.proto_params, self.n_clients,
            client_data.x.nbytes + client_data.y.nbytes
            + client_data.mask.nbytes + client_data.sizes.nbytes,
        )
        if self.algorithm.materializes_client_stack:
            _assert_client_stack_feasible(
                cfg, self.proto_params, self.n_clients
            )
        eval_pre = make_reshaper(dataset.x_test.shape[1:])
        self.eval_fn = make_eval_fn(
            self.model.apply, preprocess=eval_pre, name="server_eval"
        )
        self.evaluate = jax.jit(self.eval_fn)
        self.algorithm.prepare(
            self.model.apply,
            make_eval_fn(self.model.apply, preprocess=eval_pre),
        )
        preprocess = (
            make_decoder(client_data.sample_shape)
            if client_data.compact else None
        )
        self.algorithm.check_cohort(self.n_clients)
        self.round_fn = self.algorithm.make_round_fn(
            self.model.apply, self.optimizer, self.n_clients,
            preprocess=preprocess, client_sizes=client_data.sizes,
        )
        self.round_jit = jax.jit(self.round_fn, donate_argnums=(1,))
        self.server_init = self.server_update_jit = None
        _server = self.algorithm.make_server_update()
        if _server is not None:
            self.server_init, server_update_fn = _server
            self.server_update_jit = jax.jit(
                server_update_fn, donate_argnums=(1, 2)
            )
        self.devices = devices  # (cx, cy, cmask, sizes, eval_batches)


def _device_arrays(cfg, dataset, client_data):
    """One upload of the shared data: packed client arrays + the padded
    eval set, reused by every program of the sweep."""
    eval_np = pad_eval_set(
        dataset.x_test, dataset.y_test, cfg.eval_batch_size, flatten=True
    )
    return (
        jnp.asarray(client_data.x), jnp.asarray(client_data.y),
        jnp.asarray(client_data.mask), jnp.asarray(client_data.sizes),
        tuple(jnp.asarray(a) for a in eval_np),
    )


def lean_supported(cfg) -> bool:
    """Whether the scheduler's lean warm-program loop covers this config.

    The lean loop replays ``run_simulation``'s core round sequence
    (split -> round_jit -> optional server step -> eval -> record) with
    deferred-fetch pipelining, bit-identically — but not the per-run
    machinery around it. Anything outside this envelope falls back to a
    full ``run_simulation`` with ``compile_reused=False`` (recorded, not
    silent).
    """
    return (
        cfg.execution_mode.lower() == "vmap"
        and not cfg.multihost
        and (cfg.mesh_devices or 1) <= 1
        and cfg.client_residency.lower() == "resident"
        and getattr(cfg, "population", "static").lower() == "static"
        and cfg.rounds_per_dispatch == 1
        and cfg.async_mode.lower() == "off"
        and cfg.client_stats.lower() == "off"
        and cfg.client_valuation.lower() == "off"
        and cfg.telemetry_level.lower() == "off"
        and not cfg.profile_dir
        and not cfg.cost_model_trace
        and not (cfg.checkpoint_dir and cfg.checkpoint_every)
        and not cfg.resume
        and cfg.distributed_algorithm not in SHAPLEY_ALGORITHMS
    )


def _emit_base_record(cfg, round_idx, metrics, mean_loss, fetched_tel,
                      extra, round_seconds) -> dict:
    """One round's v1-layout base record — delegated to the simulator's
    shared ``build_base_round_record`` (the ONE copy of the field set
    and insert order), so a sweep point's records can never drift from
    solo metrics.jsonl lines."""
    from distributed_learning_simulator_tpu.simulator import (
        build_base_round_record,
    )

    return build_base_round_record(
        cfg, round_idx, metrics, mean_loss, fetched_tel, extra,
        round_seconds=round_seconds,
    )


def _warmup_seconds(times: list[float]) -> float:
    """Explicit warmup accounting shared by every strategy's point
    summary: round 0's wall minus a steady round — the trace+compile
    cost the old harnesses silently dropped with ``history[1:]``."""
    if not times:
        return 0.0
    steady = times[1:]
    return round(
        max(times[0] - (float(np.median(steady)) if steady else 0.0), 0.0),
        4,
    )


class SweepScheduler:
    """The compile-cache-aware point runner (scheduled strategy).

    Programs are cached under a seed-normalized program key — the seed
    is a pure operand (model init + RNG chain), so seed-varied config
    hashes share one compiled program. Reusable OUTSIDE run_sweep too:
    bench.py routes its repeated same-program legs through one scheduler
    so the headline's warm program serves the round_batch K=1 leg
    (warmup paid once, recorded — the ISSUE 11 small fix), and
    scripts/measure_scaling.py gets explicit per-point warmup
    accounting the silent ``history[1:]`` slice used to hide.
    """

    def __init__(self):
        self._programs: dict[str, _Program] = {}
        self._data_key = None
        self._devices = None
        # Live references to the dataset/client_data the cache was built
        # from: keeps the id()-based key honest (a collected object's id
        # can be recycled) and lets run() detect a data swap.
        self._data_ref = None
        self.points_run = 0
        self.programs_compiled = 0
        self.fallback_points = 0

    def program_key(self, cfg) -> str:
        """Seed-normalized program identity: every knob that defines the
        compiled program, with the seed (a pure operand) pinned. The
        learning rate stays IN the key — the lean loop bakes it into the
        optimizer exactly like a solo run, so lr-varied points honestly
        compile their own programs (the vmapped fleet is the strategy
        that operandizes lr)."""
        return config_hash(dataclasses.replace(cfg, seed=0))

    def _data(self, cfg, dataset, client_data):
        """Device arrays for the shared data — uploaded once. Swapping
        to DIFFERENT data invalidates every cached program (their
        round_fn closures captured the old arrays and client_sizes):
        the cache must never serve a warm program against data it was
        not built from."""
        key = (id(dataset), id(client_data), cfg.eval_batch_size)
        if self._data_key != key:
            if self._data_key is not None:
                self._programs.clear()
            self._devices = _device_arrays(cfg, dataset, client_data)
            self._data_key = key
            self._data_ref = (dataset, client_data)
        return self._devices

    def run(self, cfg, dataset=None, client_data=None):
        """Run one point; returns a result dict (history/final_accuracy/
        total_seconds/rounds_rejected/... — the run_simulation subset
        sweep consumers read) plus ``compile_reused`` and
        ``warmup_seconds``."""
        from distributed_learning_simulator_tpu.simulator import (
            run_simulation,
        )

        cfg.validate()
        dataset, client_data = _shared_data(cfg, dataset, client_data)
        self.points_run += 1
        # Same process-global compile-cache discipline as run_simulation:
        # honor (or reset) the config's persistent-cache setting before
        # any trace/compile happens.
        jax.config.update(
            "jax_compilation_cache_dir", cfg.compilation_cache_dir or None
        )
        if cfg.compilation_cache_dir:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0
            )
        if not lean_supported(cfg):
            t0 = time.perf_counter()
            result = run_simulation(
                cfg, dataset=dataset, client_data=client_data,
                setup_logging=False,
            )
            self.fallback_points += 1
            result["compile_reused"] = False
            result["warmup_seconds"] = _warmup_seconds(
                [h["round_seconds"] for h in result["history"]]
            )
            result["total_seconds"] = time.perf_counter() - t0
            return result
        # Data first: a swapped dataset/client_data clears the program
        # cache (stale closures) BEFORE any cache lookup.
        devices = self._data(cfg, dataset, client_data)
        key = self.program_key(cfg)
        prog = self._programs.get(key)
        reused = prog is not None
        if prog is None:
            prog = _Program(cfg, dataset, client_data, devices)
            self._programs[key] = prog
            self.programs_compiled += 1
        result = _run_point_lean(prog, cfg)
        result["compile_reused"] = reused
        return result


def _run_point_lean(prog: _Program, cfg) -> dict:
    """The warm-program point loop: run_simulation's core round sequence
    (host key split -> round_jit -> optional server step -> eval ->
    record), bit-identical by construction — the same eager split chain,
    the same jitted round program, the same eval scan — with the solo
    loop's deferred-fetch pipelining when nothing needs same-round
    host state. Everything outside this envelope (checkpointing,
    telemetry, streaming, ...) is gated out by ``lean_supported``.
    """
    from distributed_learning_simulator_tpu.simulator import (
        _oom_hint,
        lr_factors,
    )

    if cfg.client_chunk_size == 0:
        # Adopt the program's auto-resolved chunk only — the point keeps
        # its OWN horizon/seed/schedule knobs.
        cfg = dataclasses.replace(
            cfg, client_chunk_size=prog.cfg.client_chunk_size
        )
    algorithm = prog.algorithm
    cx, cy, cmask, sizes, eval_batches = prog.devices
    global_params = init_params(prog.model, prog.init_batch, seed=cfg.seed)
    client_state = algorithm.init_client_state(
        prog.optimizer, global_params, prog.n_clients
    )
    server_state = (
        prog.server_init(global_params)
        if prog.server_init is not None else None
    )
    key = _seed_key(cfg.seed)
    lr_active = cfg.lr_schedule.lower() != "constant"
    history: list[dict] = []
    telemetry = {"rounds_rejected": 0, "survivor_counts": []}
    prev_metrics = None
    pipelined = (
        cfg.pipeline_rounds
        and algorithm.supports_round_pipelining
        and client_state is None
        and server_state is None
    )
    t_start = time.perf_counter()
    t_prev_done = t_start

    def finalize(p):
        nonlocal prev_metrics, t_prev_done
        tel_keys = [
            k for k in ("survivor_count", "round_rejected", "participants")
            if k in p["aux"]
        ]
        fetched_metrics, fetched_loss, fetched_tel = jax.device_get(
            (p["metrics_dev"], p["mean_loss_dev"],
             {k: p["aux"][k] for k in tel_keys})
        )
        metrics = {k: float(v) for k, v in fetched_metrics.items()}
        ctx = RoundContext(
            round_idx=p["round_idx"],
            global_params=p["new_global"],
            prev_global_params=p["prev_global"],
            sizes=sizes,
            aux=p["aux"],
            metrics=metrics,
            prev_metrics=prev_metrics,
            eval_batches=eval_batches,
            log_dir=None,
        )
        extra = algorithm.post_round(ctx) or {}
        now = time.perf_counter()
        record = _emit_base_record(
            cfg, p["round_idx"], metrics, fetched_loss, fetched_tel,
            extra, now - t_prev_done,
        )
        t_prev_done = now
        if record.get("round_rejected"):
            telemetry["rounds_rejected"] += 1
        if "survivor_count" in record:
            telemetry["survivor_counts"].append(record["survivor_count"])
        history.append(record)
        prev_metrics = metrics

    pending = None
    try:
        for round_idx in range(cfg.round):
            key, round_key = jax.random.split(key)
            lr_args = (
                (jnp.float32(lr_factors(cfg, round_idx, 1)[0]),)
                if lr_active else ()
            )
            with _oom_hint(cfg, global_params, prog.n_clients):
                new_global, client_state, aux = prog.round_jit(
                    global_params, client_state, cx, cy, cmask, sizes,
                    round_key, *lr_args,
                )
                if prog.server_update_jit is not None:
                    srv_args = (global_params, new_global, server_state)
                    if "round_rejected" in aux:
                        srv_args += (aux["round_rejected"],)
                    new_global, server_state = prog.server_update_jit(
                        *srv_args
                    )
            with _oom_hint(cfg, global_params, prog.n_clients, site="eval"):
                metrics_dev = prog.evaluate(new_global, *eval_batches)
            entry = {
                "round_idx": round_idx,
                "new_global": new_global,
                "prev_global": global_params,
                "aux": aux,
                "metrics_dev": metrics_dev,
                "mean_loss_dev": aux.get("mean_client_loss", np.nan),
            }
            global_params = new_global
            if pipelined:
                prev_pending, pending = pending, entry
                if prev_pending is not None:
                    finalize(prev_pending)
            else:
                finalize(entry)
    finally:
        if pending is not None:
            finalize(pending)
    total = time.perf_counter() - t_start
    return {
        "history": history,
        "final_accuracy": history[-1]["test_accuracy"] if history else None,
        "total_seconds": total,
        "client_rounds_per_sec": (
            len(history) * prog.n_clients / max(total, 1e-9)
        ),
        "rounds_rejected": telemetry["rounds_rejected"],
        "mean_survivor_count": (
            float(np.mean(telemetry["survivor_counts"]))
            if telemetry["survivor_counts"] else None
        ),
        "warmup_seconds": _warmup_seconds(
            [h["round_seconds"] for h in history]
        ),
        "client_chunk_size": cfg.client_chunk_size,
    }


def _run_fleet(spec: SweepSpec, points, dataset, client_data,
               logger) -> list[dict]:
    """The vmapped seed/lr fleet: one jitted program, E experiments per
    dispatch (see module docstring). Returns per-point result dicts.

    ``points`` may be a subset of the spec's points (sweep resume reruns
    only the missing ones), but the program reference config — and the
    lr-factor base — is ALWAYS the spec's first point, so a resumed
    fleet's operands (hence its histories) are bit-identical to the
    uninterrupted run's.
    """
    fcfg = spec.points[0].config
    E = len(points)
    devices = _device_arrays(fcfg, dataset, client_data)
    cx, cy, cmask, sizes, eval_batches = devices
    prog = _Program(fcfg, dataset, client_data, devices)
    cfg = prog.cfg  # auto chunk resolved
    seeds = [p.config.seed for p in points]
    params_list = [
        init_params(prog.model, dataset.x_train[:1], seed=s) for s in seeds
    ]
    params_E = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *params_list
    )
    keys_E = jnp.stack([_seed_key(s) for s in seeds])
    # Per-point lr factors against the program's baked base lr (PR 5
    # lr_factors precedent): exact 1.0 for a pure seed fleet, so the
    # operand multiply is bit-exact there; an lr-varied point's factor
    # semantics match config.lr_schedule's outer multiplier.
    lr_mults = np.asarray(
        [p.config.learning_rate / fcfg.learning_rate for p in points],
        dtype=np.float32,
    )
    lr_schedule_active = cfg.lr_schedule.lower() != "constant"
    lr_active = lr_schedule_active or bool(np.any(lr_mults != 1.0))
    fleet_round = jax.jit(
        make_experiment_round_fn(prog.round_fn, lr_active),
        donate_argnums=(0, 1),
    )
    fleet_eval = jax.jit(
        make_experiment_eval_fn(prog.eval_fn, len(eval_batches))
    )
    mesh = None
    if cfg.mesh_devices and cfg.mesh_devices > 1:
        # Experiment-axis packing: each device owns E/n whole
        # experiments (spec.fleet_compatible refused non-divisible E).
        mesh = make_mesh(cfg.mesh_devices, axis_name=EXPERIMENT_AXIS)
        params_E = shard_client_data(params_E, mesh)
        keys_E = shard_client_data(keys_E, mesh)
        cx, cy, cmask = (
            replicate(cx, mesh), replicate(cy, mesh), replicate(cmask, mesh)
        )
        sizes = replicate(sizes, mesh)
        eval_batches = replicate(eval_batches, mesh)
        logger.info(
            "sweep fleet: %d experiments packed over %d mesh devices",
            E, cfg.mesh_devices,
        )
    from distributed_learning_simulator_tpu.simulator import lr_factors

    histories: list[list[dict]] = [[] for _ in points]
    telemetry = [
        {"rounds_rejected": 0, "survivor_counts": []} for _ in points
    ]
    t_start = time.perf_counter()
    t_prev = t_start
    for round_idx in range(cfg.round):
        lr_args = ()
        if lr_active:
            factor = lr_factors(cfg, round_idx, 1)[0]
            lr_vec = jnp.asarray(lr_mults * np.float32(factor))
            if mesh is not None:
                lr_vec = shard_client_data(lr_vec, mesh)
            lr_args = (lr_vec,)
        params_E, keys_E, aux = fleet_round(
            params_E, keys_E, cx, cy, cmask, sizes, *lr_args
        )
        metrics_dev = fleet_eval(params_E, *eval_batches)
        tel_keys = [
            k for k in ("survivor_count", "round_rejected", "participants")
            if k in aux
        ]
        fetched_metrics, fetched_loss, fetched_tel = jax.device_get(
            (metrics_dev, aux.get("mean_client_loss", np.full(E, np.nan)),
             {k: aux[k] for k in tel_keys})
        )
        now = time.perf_counter()
        wall = now - t_prev
        t_prev = now
        for e, point in enumerate(points):
            metrics = {
                k: float(v[e]) for k, v in fetched_metrics.items()
            }
            tel_row = {k: fetched_tel[k][e] for k in tel_keys}
            record = _emit_base_record(
                point.config, round_idx, metrics, fetched_loss[e],
                tel_row, {},
                # One dispatch trains all E experiments: the honest
                # per-experiment wall is the amortized share — what the
                # sweep_amortization_ratio measures.
                wall / E,
            )
            if record.get("round_rejected"):
                telemetry[e]["rounds_rejected"] += 1
            if "survivor_count" in record:
                telemetry[e]["survivor_counts"].append(
                    record["survivor_count"]
                )
            histories[e].append(record)
    total = time.perf_counter() - t_start
    results = []
    for e, point in enumerate(points):
        results.append({
            "history": histories[e],
            "final_accuracy": (
                histories[e][-1]["test_accuracy"] if histories[e] else None
            ),
            "total_seconds": total / E,
            "rounds_rejected": telemetry[e]["rounds_rejected"],
            "mean_survivor_count": (
                float(np.mean(telemetry[e]["survivor_counts"]))
                if telemetry[e]["survivor_counts"] else None
            ),
            # The fleet compiles once; the compile is attributed to
            # point 0 so mean(compile_reused) = 1 - programs/points —
            # the same accounting as the scheduler.
            "compile_reused": e > 0,
            "warmup_seconds": _warmup_seconds(
                [h["round_seconds"] for h in histories[e]]
            ),
            "client_chunk_size": cfg.client_chunk_size,
        })
    return results


def _point_path(sweep_dir: str, index: int) -> str:
    return os.path.join(sweep_dir, f"point_{index:04d}.json")


def _persist_point(sweep_dir, point, summary, records) -> None:
    os.makedirs(sweep_dir, exist_ok=True)
    with open(_point_path(sweep_dir, point.index), "w") as f:
        json.dump(summary, f)
    with open(os.path.join(sweep_dir, "metrics.jsonl"), "a") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")


def _load_point(sweep_dir, point) -> dict | None:
    """A previously persisted result for this point, or None. The stored
    config_hash must match — a resumed sweep whose points changed must
    re-run them, never stitch foreign histories."""
    path = _point_path(sweep_dir, point.index)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            saved = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if saved.get("config_hash") != config_hash(point.config) or (
        saved.get("rounds") != point.config.round
    ):
        return None
    return saved


def run_sweep(spec_or_config, dataset=None, client_data=None) -> dict:
    """Run a multi-experiment sweep; returns the sweep result dict.

    Accepts a validated :class:`SweepSpec` or an ``ExperimentConfig``
    whose sweep knobs are set (``SweepSpec.from_config``). ``dataset`` /
    ``client_data`` are the same injection points as ``run_simulation``
    — the whole sweep shares them (the base config's data).
    """
    spec = (
        spec_or_config if isinstance(spec_or_config, SweepSpec)
        else SweepSpec.from_config(spec_or_config)
    )
    spec.validate()
    logger = get_logger()
    strategy = spec.resolve_strategy()
    base = spec.base
    if base.compilation_cache_dir:
        jax.config.update(
            "jax_compilation_cache_dir", base.compilation_cache_dir
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    dataset, client_data = _shared_data(base, dataset, client_data)
    crash_after = os.environ.get(_CRASH_ENV)
    crash_after = int(crash_after) if crash_after else None
    results: dict[int, dict] = {}
    resumed: set[int] = set()
    if spec.sweep_dir and spec.resume:
        for point in spec.points:
            saved = _load_point(spec.sweep_dir, point)
            if saved is not None:
                results[point.index] = saved
                resumed.add(point.index)
        if resumed:
            logger.info(
                "sweep resume: %d/%d point(s) loaded from %s",
                len(resumed), len(spec.points), spec.sweep_dir,
            )
    if spec.sweep_dir and not spec.resume:
        # Fresh sweep into an existing dir: clear the previous sweep's
        # artifacts so records never interleave two sweeps (point files
        # would be overwritten anyway; metrics.jsonl appends).
        stale = os.path.join(spec.sweep_dir, "metrics.jsonl")
        if os.path.exists(stale):
            os.remove(stale)
        for p in spec.points:
            path = _point_path(spec.sweep_dir, p.index)
            if os.path.exists(path):
                os.remove(path)
    todo = [p for p in spec.points if p.index not in resumed]
    executed = 0
    t_start = time.perf_counter()

    def record_point(point, run_result, strategy_name):
        nonlocal executed
        sweep_rec = _sweep_record(
            point, strategy_name, run_result.get("compile_reused", False),
            # The EXECUTED fleet's width (a resumed fleet re-runs only
            # the missing points).
            experiments=(
                len(todo) if strategy_name == "vmapped" else None
            ),
        )
        records = [
            build_round_record(dict(h), sweep=sweep_rec)
            for h in run_result["history"]
        ]
        summary = {
            "index": point.index,
            "seed": int(point.config.seed),
            "learning_rate": float(point.config.learning_rate),
            "overrides": point.overrides,
            "config_hash": config_hash(point.config),
            "rounds": point.config.round,
            "strategy": strategy_name,
            "compile_reused": bool(run_result.get("compile_reused", False)),
            "warmup_seconds": run_result.get("warmup_seconds"),
            "final_accuracy": run_result.get("final_accuracy"),
            "total_seconds": round(run_result.get("total_seconds", 0.0), 4),
            "rounds_rejected": run_result.get("rounds_rejected", 0),
            "history": run_result["history"],
        }
        results[point.index] = summary
        if spec.sweep_dir:
            _persist_point(spec.sweep_dir, point, summary, records)
        executed += 1
        if crash_after is not None and executed >= crash_after:
            raise RuntimeError(
                f"sweep chaos crash after {executed} point(s) "
                f"({_CRASH_ENV})"
            )

    if strategy == "vmapped":
        # (A fully-resumed fleet has nothing to run — the strategy label
        # stays 'vmapped', matching the persisted per-point records.)
        if todo:
            fleet_results = _run_fleet(
                spec, todo, dataset, client_data, logger
            )
            for point, rr in zip(todo, fleet_results):
                record_point(point, rr, "vmapped")
        programs_compiled = 1 if todo else 0
    else:
        scheduler = SweepScheduler()
        # config_hash grouping: points of one hash run consecutively so
        # each group streams through its (seed-normalized) warm program.
        groups: dict[str, list] = {}
        for p in todo:
            groups.setdefault(config_hash(p.config), []).append(p)
        for group_points in groups.values():
            for point in group_points:
                rr = scheduler.run(
                    point.config, dataset=dataset, client_data=client_data
                )
                record_point(point, rr, "scheduled")
        programs_compiled = (
            scheduler.programs_compiled + scheduler.fallback_points
        )
    total = time.perf_counter() - t_start
    ordered = [results[p.index] for p in spec.points]
    n_exec = len(todo)
    reuse = (
        sum(1 for p in spec.points
            if p.index not in resumed and results[p.index]["compile_reused"])
        / n_exec if n_exec else None
    )
    finals = [
        (r["final_accuracy"], -r["index"]) for r in ordered
        if r["final_accuracy"] is not None
    ]
    winner = None
    if finals:
        best = max(finals)
        winner_idx = -best[1]
        winner = {
            "point": winner_idx,
            "seed": ordered[winner_idx]["seed"],
            "learning_rate": ordered[winner_idx]["learning_rate"],
            "final_accuracy": best[0],
        }
    out = {
        "strategy": strategy,
        "points": [
            {**r, "resumed": r["index"] in resumed} for r in ordered
        ],
        "n_points": len(spec.points),
        "executed_points": n_exec,
        "resumed_points": len(resumed),
        "programs_compiled": programs_compiled if n_exec else 0,
        "compile_reuse_fraction": reuse,
        "winner": winner,
        "total_seconds": total,
        "experiments_per_hour": (
            n_exec / total * 3600.0 if n_exec and total > 0 else None
        ),
        "sweep_dir": spec.sweep_dir,
    }
    # $/sweep (telemetry/costmodel.py): price the compiled program once,
    # multiply by the sweep's round occupancy per topology. Attached
    # when the base config names a trace of the (shared) program.
    if base.cost_model_trace:
        from distributed_learning_simulator_tpu.telemetry.costmodel import (
            ledger_totals,
            sweep_cost_record,
        )
        from distributed_learning_simulator_tpu.utils.tracing import (
            categorize_ops,
        )

        ledger = categorize_ops(base.cost_model_trace)
        if ledger and ledger_totals(ledger)["bytes_gb"] > 0:
            out["costmodel_sweep"] = sweep_cost_record(
                ledger,
                trace_rounds=base.cost_model_trace_rounds,
                points=len(spec.points),
                rounds_total=sum(r["rounds"] for r in ordered),
                programs_compiled=out["programs_compiled"],
                # Compile bookkeeping over the points THIS run executed
                # (a partial resume compiled programs only for them) —
                # keeps the cost record's reuse fraction equal to the
                # result dict's.
                executed_points=n_exec,
                anchor=base.cost_model_topology,
            )
        else:
            logger.warning(
                "cost_model_trace %r holds no byte-annotated device-op "
                "events; $/sweep pricing disabled", base.cost_model_trace,
            )
            out["costmodel_sweep"] = None
    logger.info(
        "sweep finished: %d point(s) (%d resumed), strategy=%s, "
        "programs_compiled=%s, compile_reuse=%.2f, %.2fs",
        len(spec.points), len(resumed), strategy,
        out["programs_compiled"],
        reuse if reuse is not None else float("nan"), total,
    )
    return out
