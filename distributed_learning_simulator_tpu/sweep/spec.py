"""Sweep specification: many experiments as first-class traffic.

Production traffic for a simulator is *many concurrent experiments*, not
one (FL_PyTorch frames federated simulation as an optimization-research
sweep workload; ROADMAP item 1). A :class:`SweepSpec` turns a base
:class:`~distributed_learning_simulator_tpu.config.ExperimentConfig`
plus a list of per-point overrides into a validated experiment fleet and
resolves HOW the fleet executes (sweep/engine.py):

* ``vmapped`` — every point agrees on every program-defining knob except
  the :data:`FLEET_AXES` (seed, learning_rate). The points stack on a new
  leading experiment axis and run as ONE jitted program: per-point seeds
  become stacked model inits + per-experiment RNG key chains (point ``i``
  is bit-identical to a solo run with that seed on the shared data), and
  per-point learning rates become a length-E f32 operand vector riding
  the PR 5 ``lr_factors`` precedent. Compile is paid once for the whole
  fleet.
* ``scheduled`` — heterogeneous points are grouped by
  ``utils/reporting.config_hash`` (the program-defining-knob identity)
  and each group runs sequentially through one warm program; programs
  are cached under a seed-normalized program key (the seed is a pure
  operand — model init + the RNG chain — so seed-varied groups share one
  compiled program), and per-point compile reuse is recorded.
* ``auto`` (default) — ``vmapped`` when every point is fleet-compatible,
  else ``scheduled``.

Data contract: the whole sweep shares the BASE config's dataset and
client partition (data seed = base seed). Each point's ``seed`` drives
model init and the training RNG chain only — which is what makes a
vmapped point's history bit-identical to
``run_simulation(replace(base, seed=s), dataset=shared, client_data=
shared)``, the injected-data solo counterpart (tests/test_sweep.py).
"""

from __future__ import annotations

import dataclasses
import json

from distributed_learning_simulator_tpu.config import (
    SHAPLEY_ALGORITHMS,
    SWEEP_STRATEGIES,
    ExperimentConfig,
)
from distributed_learning_simulator_tpu.utils.reporting import config_hash

#: Knobs the vmapped fleet turns into per-experiment operands: the seed
#: (stacked model inits + per-experiment key chains) and the learning
#: rate (a length-E factor vector against the base lr, multiplied into
#: the schedule factor exactly like config.lr_schedule's per-round
#: operand). Everything else is a program-defining knob a fleet cannot
#: vary — such points go through the scheduled strategy.
FLEET_AXES = ("seed", "learning_rate")


@dataclasses.dataclass
class SweepPoint:
    """One experiment of the sweep: the base config plus overrides."""

    index: int
    overrides: dict
    config: ExperimentConfig

    @property
    def seed(self) -> int:
        return self.config.seed

    @property
    def learning_rate(self) -> float:
        return self.config.learning_rate


def _parse_points_field(value):
    """``config.sweep_points`` accepts a JSON string (CLI) or a list of
    override dicts (library callers); normalize to a list of dicts."""
    if value in (None, "", []):
        return None
    if isinstance(value, str):
        value = json.loads(value)
    if not isinstance(value, list) or not all(
        isinstance(p, dict) for p in value
    ):
        raise ValueError(
            "sweep_points must be a JSON list of per-point override "
            'objects, e.g. \'[{"learning_rate": 0.05}, '
            '{"learning_rate": 0.1}]\''
        )
    return value


def _parse_seeds_field(value):
    """``config.sweep_seeds``: comma-separated seed list (or a list)."""
    if value in (None, "", []):
        return None
    if isinstance(value, str):
        seeds = [int(s) for s in value.split(",") if s.strip()]
    else:
        seeds = [int(s) for s in value]
    if not seeds:
        return None
    return seeds


class SweepSpec:
    """A validated multi-experiment sweep (see module docstring)."""

    def __init__(self, base: ExperimentConfig, points: list[dict],
                 strategy: str = "auto", sweep_dir: str | None = None,
                 resume: bool = False):
        self.base = base
        self.strategy = strategy
        self.sweep_dir = sweep_dir
        self.resume = resume
        # Point configs are SOLO experiment configs: the sweep knobs are
        # stripped so a point's config_hash equals the hash of the same
        # experiment run standalone (the comparability the scheduler's
        # grouping and the bench's serial baseline both rest on).
        strip = dict(
            sweep_seeds=None, sweep_points=None, sweep_strategy="auto",
            sweep_dir=None, sweep_resume=False,
        )
        self.points = []
        for i, ov in enumerate(points):
            try:
                cfg = dataclasses.replace(base, **{**strip, **ov})
            except TypeError as e:
                raise ValueError(
                    f"sweep point {i} overrides unknown config field(s): "
                    f"{sorted(ov)} ({e})"
                ) from e
            self.points.append(
                SweepPoint(index=i, overrides=dict(ov), config=cfg)
            )
        self._validated = False

    @classmethod
    def from_config(cls, config: ExperimentConfig) -> "SweepSpec":
        """Build the spec from the config's sweep knobs: ``sweep_seeds``
        (comma-separated seed fleet) x ``sweep_points`` (JSON override
        list) — when both are given, every override runs at every seed
        (the seeds-x-hyperparameters grid)."""
        seeds = _parse_seeds_field(config.sweep_seeds)
        point_dicts = _parse_points_field(config.sweep_points)
        if seeds is None and point_dicts is None:
            raise ValueError(
                "no sweep requested: set sweep_seeds (e.g. '0,1,2,3') "
                "and/or sweep_points (a JSON list of override objects)"
            )
        if seeds is None:
            grid = [dict(p) for p in point_dicts]
        elif point_dicts is None:
            grid = [{"seed": s} for s in seeds]
        else:
            grid = [
                {**p, "seed": s} for p in point_dicts for s in seeds
            ]
        return cls(
            config, grid, strategy=config.sweep_strategy,
            sweep_dir=config.sweep_dir, resume=config.sweep_resume,
        )

    @staticmethod
    def active(config) -> bool:
        """Whether this config asks for a sweep (the front-door dispatch
        in ``simulator.main`` / ``__main__``)."""
        return bool(
            _parse_seeds_field(getattr(config, "sweep_seeds", None))
            or _parse_points_field(getattr(config, "sweep_points", None))
        )

    # ---- validation / refusals --------------------------------------------
    def validate(self) -> "SweepSpec":
        if not self.points:
            raise ValueError("a sweep needs at least one point")
        if self.strategy not in SWEEP_STRATEGIES:
            raise ValueError(
                f"unknown sweep strategy {self.strategy!r}; known: "
                + ", ".join(SWEEP_STRATEGIES)
            )
        seen: dict[tuple, int] = {}
        for p in self.points:
            # Per-point config validation first: a typo'd override fails
            # with the normal config error, named with its point index.
            try:
                p.config.validate()
            except ValueError as e:
                raise ValueError(
                    f"sweep point {p.index} ({p.overrides!r}) is invalid: "
                    f"{e}"
                ) from e
            cfg = p.config
            if cfg.execution_mode.lower() == "threaded":
                raise ValueError(
                    "execution_mode='threaded' does not support sweeps: "
                    "the thread-per-client oracle owns one OS thread per "
                    "client per experiment and shares no compiled "
                    "program; run threaded points as solo runs"
                )
            if cfg.distributed_algorithm in SHAPLEY_ALGORITHMS:
                raise ValueError(
                    f"algorithm {cfg.distributed_algorithm!r} does not "
                    "support sweeps: its post_round drives data-dependent "
                    "subset evaluation that must observe every round "
                    "synchronously — neither a vmapped fleet nor a "
                    "shared warm program can serve it; run Shapley "
                    "configs as solo runs"
                )
            if (
                cfg.client_residency.lower() == "streamed"
                and cfg.rounds_per_dispatch > 1
            ):
                raise ValueError(
                    "client_residency='streamed' with rounds_per_dispatch"
                    " > 1 does not compose with sweeps: the scheduler "
                    "cannot host-replay K stacked cohort plans across "
                    "points sharing one streamer; set "
                    "rounds_per_dispatch=1 or client_residency='resident'"
                )
            if cfg.multihost:
                raise ValueError(
                    "sweeps do not compose with multihost: every process "
                    "would re-run the whole point list; shard the sweep "
                    "across hosts by splitting the point list instead"
                )
            key = (config_hash(cfg), cfg.round)
            if key in seen:
                raise ValueError(
                    f"sweep points {seen[key]} and {p.index} are "
                    "identical experiments (same program-defining knobs, "
                    "seed, and horizon) — a duplicate point would just "
                    "recompute the same history; drop one or vary a knob"
                )
            seen[key] = p.index
        if self.strategy == "vmapped":
            ok, reason = self.fleet_compatible()
            if not ok:
                raise ValueError(
                    f"sweep_strategy='vmapped' refused: {reason}; use "
                    "sweep_strategy='scheduled' (or 'auto')"
                )
        self._validated = True
        return self

    def fleet_compatible(self) -> tuple[bool, str]:
        """Whether every point can join ONE vmapped fleet.

        Returns ``(ok, reason)`` — the reason names the first blocking
        feature so 'auto' falling back to 'scheduled' (and 'vmapped'
        refusing) is always explainable.
        """
        base = self.points[0].config
        for p in self.points:
            stripped = {
                k: v for k, v in p.overrides.items() if k not in FLEET_AXES
            }
            if dataclasses.replace(
                p.config, **{a: getattr(base, a) for a in FLEET_AXES}
            ) != dataclasses.replace(
                base, **{a: getattr(base, a) for a in FLEET_AXES}
            ):
                return False, (
                    f"point {p.index} overrides program-defining knobs "
                    f"beyond the fleet axes {FLEET_AXES}: "
                    f"{sorted(stripped)} — a vmapped fleet shares one "
                    "compiled program, so only operand-valued knobs may "
                    "vary"
                )
        cfg = base
        if cfg.distributed_algorithm not in ("fed",):
            return False, (
                f"algorithm {cfg.distributed_algorithm!r} does not "
                "support the experiment-vmapped fleet (fed only: "
                "fed_quant's post_round computes per-model payload "
                "analytics the stacked fleet cannot attribute; sign_SGD "
                "takes no lr operand and may carry per-client momentum)"
            )
        if not cfg.reset_client_optimizer:
            return False, (
                "reset_client_optimizer=False keeps per-client optimizer "
                "state — a vmapped fleet would hold E full per-client "
                "state stacks resident"
            )
        if cfg.client_eval is True:
            return False, (
                "client_eval=True materializes the per-client parameter "
                "stack per experiment and its post_round evaluates every "
                "client's model per point"
            )
        if cfg.aggregation.lower() != "mean":
            return False, (
                f"aggregation={cfg.aggregation!r} materializes the "
                "per-client parameter stack — E resident stacks defeat "
                "the fleet's memory envelope"
            )
        if cfg.client_stats.lower() == "on" or (
            cfg.client_valuation.lower() == "on"
        ):
            return False, (
                "client_stats/client_valuation host-side detectors are "
                "per-run machinery (median/MAD flags, the streaming "
                "valuation fold) not yet stacked over an experiment axis"
            )
        if cfg.async_mode.lower() == "on":
            return False, (
                "async_mode='on' carries a staleness-buffer state tree "
                "per experiment; the fleet does not stack it"
            )
        if getattr(cfg, "population", "static").lower() != "static":
            return False, (
                "population='dynamic' grows the client axis mid-run "
                "(robustness/population.py); experiment-axis stacking "
                "assumes a fixed N shared by every point, so a vmapped "
                "fleet cannot serve it — the scheduled strategy runs "
                "each dynamic point through a full run_simulation"
            )
        if cfg.client_residency.lower() != "resident":
            return False, (
                "client_residency='streamed' pins the cohort pipeline to "
                "one host store/streamer pair; the fleet runs resident "
                "data shared across experiments"
            )
        if cfg.rounds_per_dispatch > 1:
            return False, (
                "rounds_per_dispatch > 1 fuses the host round loop into "
                "a scan per run; the fleet owns its own round loop"
            )
        if cfg.server_optimizer_name.lower() not in ("none", ""):
            return False, (
                "a server optimizer keeps per-experiment server state; "
                "the fleet does not stack it"
            )
        if cfg.telemetry_level.lower() != "off":
            return False, (
                "telemetry_level != 'off' attributes phase timings and "
                "recompiles per run; a fleet dispatch is one program for "
                "all points"
            )
        if cfg.checkpoint_dir or cfg.resume:
            return False, (
                "per-round checkpointing is per-run state; sweep-level "
                "checkpoint/resume (sweep_dir) covers interrupted sweeps"
            )
        if cfg.profile_dir or cfg.cost_model_trace:
            return False, (
                "profiling / cost-model trace attachment are per-run "
                "analyses"
            )
        if (
            cfg.mesh_devices and cfg.mesh_devices > 1
            and len(self.points) % cfg.mesh_devices != 0
        ):
            return False, (
                f"experiment-axis mesh packing needs the point count "
                f"({len(self.points)}) to be a multiple of mesh_devices "
                f"({cfg.mesh_devices}) — each device owns whole "
                "experiments"
            )
        return True, ""

    def resolve_strategy(self) -> str:
        """The strategy the engine will run (validate() first)."""
        if not self._validated:
            self.validate()
        if self.strategy == "vmapped":
            return "vmapped"
        if self.strategy == "scheduled":
            return "scheduled"
        ok, _ = self.fleet_compatible()
        return "vmapped" if ok else "scheduled"
