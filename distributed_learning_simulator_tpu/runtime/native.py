"""ctypes bindings for the native (C++) runtime: queue + thread pool.

Provides the reference's external L1 runtime surface natively
(SURVEY §2.4; reference import sites: servers/server.py:1-3 ThreadTaskQueue /
TorchProcessTaskQueue, simulator.py:5-6 ThreadPool, servers/fed_server.py:3
RepeatedResult):

  * :class:`NativeTaskQueue` — blocking rendezvous queue. Workers
    ``add_task(obj)`` and block on ``get_result()``; the server side either
    polls ``get_task()`` or registers ``worker_fun`` (a callback run on a
    dedicated native thread for every task — the reference queue's
    constructor contract, servers/server.py:10-17). A ``worker_fun`` return
    of ``None`` means no reply; a :class:`RepeatedResult` broadcasts its
    payload N times (reference fed_server.py:88-91).
  * :class:`NativeThreadPool` — ``exec(fn, *args)`` / ``join_pending()`` /
    ``stop()`` (reference simulator.py:60-71).

Payloads cross the C boundary as pickle bytes. The shared library is built
from ``native/dls_runtime.cc`` on first use if missing (g++, ~1s).
"""

from __future__ import annotations

import ctypes
import os
import pickle
import subprocess
import threading
from dataclasses import dataclass
from typing import Any, Callable

from distributed_learning_simulator_tpu.utils.logging import get_logger

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdls_runtime.so")
_lib = None
_lib_lock = threading.Lock()

_CALLBACK_T = ctypes.CFUNCTYPE(None, ctypes.c_uint64)


def _build_library() -> None:
    src = os.path.join(_NATIVE_DIR, "dls_runtime.cc")
    if not os.path.exists(src):
        raise FileNotFoundError(f"native source not found: {src}")
    get_logger().info("building native runtime: %s", _LIB_PATH)
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-fPIC", "-Wall", "-shared",
         "-o", _LIB_PATH, src, "-lpthread"],
        check=True, capture_output=True,
    )


def _get_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        src = os.path.join(_NATIVE_DIR, "dls_runtime.cc")
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
        ):
            _build_library()
        lib = ctypes.CDLL(_LIB_PATH)
        lib.dlsq_create.restype = ctypes.c_void_p
        lib.dlsq_destroy.argtypes = [ctypes.c_void_p]
        lib.dlsq_add_task.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t
        ]
        lib.dlsq_get_task.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.dlsq_put_result.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int
        ]
        lib.dlsq_get_result.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
            ctypes.POINTER(ctypes.c_size_t),
        ]
        lib.dlsq_stop.argtypes = [ctypes.c_void_p]
        lib.dlsq_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
        lib.dlsp_create.restype = ctypes.c_void_p
        lib.dlsp_create.argtypes = [ctypes.c_int]
        lib.dlsp_destroy.argtypes = [ctypes.c_void_p]
        lib.dlsp_submit.argtypes = [ctypes.c_void_p, _CALLBACK_T, ctypes.c_uint64]
        lib.dlsp_join_pending.argtypes = [ctypes.c_void_p]
        lib.dlsp_stop.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def native_available() -> bool:
    """True if the native library is present or buildable."""
    try:
        _get_lib()
        return True
    except Exception:  # noqa: BLE001 - availability probe
        return False


@dataclass
class RepeatedResult:
    """One-to-N broadcast wrapper (reference fed_server.py:3,19-24)."""

    data: Any
    num: int


class NativeTaskQueue:
    """Blocking rendezvous queue backed by the C++ runtime.

    ``worker_fun``: if given, a dedicated native-backed thread consumes every
    task and calls ``worker_fun(task, extra_args)``; a non-None return is
    broadcast (``RepeatedResult``) or enqueued once (any other object) —
    the reference queue contract (servers/server.py:11-17,
    fed_server.py:68-91).
    """

    def __init__(self, worker_fun: Callable | None = None, extra_args=None):
        self._lib = _get_lib()
        self._q = self._lib.dlsq_create()
        self._stopped = False
        self._server_thread = None
        if worker_fun is not None:
            self._server_thread = threading.Thread(
                target=self._serve, args=(worker_fun, extra_args), daemon=True
            )
            self._server_thread.start()

    def _take(self, getter) -> Any | None:
        out = ctypes.POINTER(ctypes.c_char)()
        out_len = ctypes.c_size_t()
        rc = getter(self._q, ctypes.byref(out), ctypes.byref(out_len))
        if rc != 0:
            return None  # stopped
        try:
            payload = ctypes.string_at(out, out_len.value)
        finally:
            self._lib.dlsq_free(out)
        return pickle.loads(payload)

    def _serve(self, worker_fun, extra_args):
        while True:
            task = self._take(self._lib.dlsq_get_task)
            if task is None:
                return
            result = worker_fun(task, extra_args)
            if result is None:
                continue
            try:
                if isinstance(result, RepeatedResult):
                    self.put_result(result.data, copies=result.num)
                else:
                    self.put_result(result, copies=1)
            except RuntimeError:
                # stop() raced the final broadcast; nobody is listening.
                return

    # ---- worker side -------------------------------------------------------
    def add_task(self, obj: Any) -> None:
        payload = pickle.dumps(obj)
        rc = self._lib.dlsq_add_task(self._q, payload, len(payload))
        if rc != 0:
            raise RuntimeError("queue is stopped")

    def get_result(self) -> Any:
        result = self._take(self._lib.dlsq_get_result)
        if result is None:
            raise RuntimeError("queue is stopped")
        return result

    # ---- server side -------------------------------------------------------
    def get_task(self) -> Any | None:
        """Blocking pop of one task; None once stopped."""
        return self._take(self._lib.dlsq_get_task)

    def put_result(self, obj: Any, copies: int = 1) -> None:
        self.put_result_pickled(pickle.dumps(obj), copies=copies)

    def put_result_pickled(self, payload: bytes, copies: int = 1) -> None:
        """Enqueue an already-pickled payload — lets a broadcast to N
        per-worker queues serialize the object once instead of N times."""
        rc = self._lib.dlsq_put_result(self._q, payload, len(payload), copies)
        if rc != 0:
            raise RuntimeError("queue is stopped")

    @property
    def stopped(self) -> bool:
        """True once stop() was called (or the C++ queue was stopped via
        this wrapper); lets callers distinguish the benign stopped-queue
        race from a genuine enqueue failure."""
        return self._stopped

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._lib.dlsq_stop(self._q)
            if (
                self._server_thread is not None
                and self._server_thread is not threading.current_thread()
            ):
                # Full join, no timeout: dlsq_stop makes get_task return,
                # so the serve thread exits as soon as the CURRENT callback
                # finishes — which may be the final round's aggregation +
                # evaluation. A timed join could return while that callback
                # is still appending to history, silently losing the last
                # round's record. (The current-thread guard lets a callback
                # itself initiate shutdown on server-side errors.)
                self._server_thread.join()

    def __del__(self):
        try:
            self.stop()
            self._lib.dlsq_destroy(self._q)
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass


class NativeThreadPool:
    """Thread pool running Python callables on native threads.

    Reference surface: ``ThreadPool.exec(fn, **kw)`` + ``stop()``
    (simulator.py:60-71). Callbacks cross into Python via a ctypes
    trampoline (which re-acquires the GIL); jitted jax computations release
    the GIL during device execution, so per-client training overlaps.
    """

    def __init__(self, n_threads: int):
        self._lib = _get_lib()
        self._pool = self._lib.dlsp_create(n_threads)
        self._tasks: dict[int, tuple] = {}
        self._results: dict[int, Any] = {}
        self._errors: dict[int, BaseException] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        # The trampoline must outlive every pending call: keep a reference.
        self._trampoline = _CALLBACK_T(self._run_task)
        self._stopped = False

    def _run_task(self, task_id: int) -> None:
        with self._lock:
            fn, args, kwargs = self._tasks.pop(task_id)
        try:
            result = fn(*args, **kwargs)
            with self._lock:
                self._results[task_id] = result
        except BaseException as e:  # noqa: BLE001 - surfaced via results()
            with self._lock:
                self._errors[task_id] = e

    def exec(self, fn: Callable, *args, **kwargs) -> int:
        """Submit ``fn(*args, **kwargs)``; returns a task id."""
        with self._lock:
            task_id = self._next_id
            self._next_id += 1
            self._tasks[task_id] = (fn, args, kwargs)
        rc = self._lib.dlsp_submit(self._pool, self._trampoline, task_id)
        if rc != 0:
            with self._lock:
                self._tasks.pop(task_id, None)
            raise RuntimeError("pool is stopped")
        return task_id

    def join_pending(self) -> None:
        """Block until every submitted task has run."""
        self._lib.dlsp_join_pending(self._pool)

    def poll(self) -> tuple[int, int, bool]:
        """Non-blocking progress probe: (completed, submitted, any_error).

        Lets a coordinator wait for workers WITHOUT committing to a blocking
        join — on the first error it can tear down the rendezvous queues so
        peers blocked in get_result unblock instead of deadlocking on a
        barrier that can never fill."""
        with self._lock:
            done = len(self._results) + len(self._errors)
            return done, self._next_id, bool(self._errors)

    def results(self) -> dict[int, Any]:
        """Completed results by task id; raises the first captured error."""
        with self._lock:
            if self._errors:
                raise next(iter(self._errors.values()))
            return dict(self._results)

    def stop(self) -> None:
        """Join all pending work and shut the pool down (reference
        ThreadPool.stop, simulator.py:71)."""
        if not self._stopped:
            self.join_pending()
            self._stopped = True
            self._lib.dlsp_stop(self._pool)

    def __del__(self):
        try:
            self.stop()
            self._lib.dlsp_destroy(self._pool)
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass
