from distributed_learning_simulator_tpu.runtime.native import (
    NativeTaskQueue,
    NativeThreadPool,
    RepeatedResult,
    native_available,
)

__all__ = [
    "NativeTaskQueue",
    "NativeThreadPool",
    "RepeatedResult",
    "native_available",
]
