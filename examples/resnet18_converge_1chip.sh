#!/bin/bash
# Flagship ResNet-18 convergence run: 1000 clients x 150 rounds at
# accuracy-bearing hyperparameters (lr 0.02 + cosine decay; the bench lr
# 0.1 is too hot for the GroupNorm ResNet from scratch at 2 steps/round).
# Measured (docs/PERFORMANCE.md): final test accuracy 0.9459 (bf16+SR)
# vs 0.9453 (f32) on the CIFAR-shaped surrogate in round 3; 0.9490 in
# round 4 (folded stem); round-5 rerun reaches 0.9498 at a sustained
# 438.5 c*r/s over all 150 rounds — the pod-rate margin holds for
# converged runs, not just short benches.
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name cifar10 --model_name resnet18 \
  --distributed_algorithm fed \
  --worker_number 1000 --round 150 --epoch 1 \
  --learning_rate 0.02 --lr_schedule cosine --lr_min_factor 0.1 \
  --momentum 0.9 --batch_size 25 \
  --client_chunk_size 40 --local_compute_dtype bfloat16 \
  --eval_batch_size 10000 --log_level INFO
