#!/bin/bash
# Quantized FedAvg: straight-through-estimator QAT in the client loss,
# 256-level stochastic-rounded parameter exchange both directions, analytic
# compression-ratio reporting (history rows carry uplink/downlink ratios).
# At flagship scale (1000 clients x ResNet-18): 401 c*r/s (1.20x the
# v5e-8 pod-rate on one chip) and 0.9418 converged accuracy over 150
# rounds — ~0.8 points below unquantized, the 4x wire format's cost
# (docs/PERFORMANCE.md round 5).
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name mnist --model_name lenet5 \
  --distributed_algorithm fed_quant \
  --worker_number 8 --round 5 --epoch 1 --learning_rate 0.1 \
  --quant_levels 256 --log_level INFO
