#!/bin/bash
# Open-world dynamic population (docs/ROBUSTNESS.md § Dynamic
# populations): 100 clients grow toward ~10x over 30 rounds (20
# registrations/round, shards drawn over the growing index space), 2% of
# alive clients depart per round (masked out of the hashed sampler's
# stream, never resampled; a same-round departure is quorum-visible),
# and a planted 10-client cohort drifts toward graded label noise that
# the always-on streaming valuation tracks. The cohort stays pinned at
# 25 clients/round, so the compiled program never changes shape while N
# grows. CRC-verified checkpoints persist the registration-stream
# cursor + alive mask + grown shards: kill this run at any point and
# --resume true stitches bit-identically (chaos proof:
# tests/test_chaos_resume.py mid-growth variant).
set -e
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name cifar10 --model_name cnn_tpu \
  --distributed_algorithm fed \
  --worker_number 100 --round 30 --epoch 1 --learning_rate 0.1 \
  --momentum 0.9 --batch_size 25 --participation_fraction 0.25 \
  --client_residency streamed --participation_sampler hashed \
  --population dynamic --join_rate 20 --depart_rate 0.02 \
  --drift_fraction 0.1 --drift_factor 0.8 \
  --client_stats on --client_valuation on \
  --min_survivors 5 \
  --checkpoint_dir ckpt_population --checkpoint_every 5 \
  --checkpoint_keep_last 3 \
  --log_level INFO
# Render the population section (N-over-time sparkline, join/depart
# counts, drift overlay on the valuation tables) from the newest run:
python scripts/report_run.py "$(ls -dt log/fed/cifar10/cnn_tpu/*_artifacts | head -1)"
