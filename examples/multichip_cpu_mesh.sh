#!/bin/bash
# Multi-chip sharding validated on 8 virtual CPU devices (no TPU pod needed):
# the client axis gets PartitionSpec("clients") over a 1-D mesh and
# aggregation lowers to cross-device collectives. On a real pod slice, drop
# the two env vars and set --mesh_devices to the real chip count.
XLA_FLAGS="--xla_force_host_platform_device_count=8" \
DLS_ALLOW_CPU_MESH_FALLBACK=1 \
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name synthetic --model_name mlp \
  --distributed_algorithm fed \
  --worker_number 64 --round 3 --epoch 1 --learning_rate 0.1 \
  --mesh_devices 8 --n_train 4096 --n_test 512 --log_level INFO
