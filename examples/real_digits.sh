#!/bin/bash
# REAL pixels with zero network: scikit-learn's bundled handwritten-digits
# images (1797 8x8 scans) as dataset `digits`. All five algorithms reach
# 96-97% test accuracy on this config (docs/ACCURACY.md); swap
# --distributed_algorithm to try the others (sign_SGD wants lr 0.01).
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name digits --model_name mlp \
  --distributed_algorithm fed \
  --worker_number 4 --round 10 --epoch 2 --learning_rate 0.1 \
  --batch_size 25 --log_level INFO
