#!/usr/bin/env bash
# Multi-experiment sweep: an 8-seed fleet of the synthetic MLP config as
# ONE vmapped program (sweep/engine.py — compile paid once, every
# point's history bit-identical to a solo run with that seed on the
# shared data), with per-point results + schema-v8 records persisted
# under --sweep_dir. Re-run with --sweep_resume true after an interrupt
# to execute only the missing points (bit-identical stitching).
#
# Render the sweep afterwards (per-point accuracy table, winner line,
# compile-reuse summary):
#   python scripts/report_run.py "$SWEEP_DIR"
set -euo pipefail
cd "$(dirname "$0")/.."

SWEEP_DIR="${SWEEP_DIR:-/tmp/dls_sweep_seeds}"

python -m distributed_learning_simulator_tpu \
  --dataset_name synthetic \
  --model_name mlp \
  --distributed_algorithm fed \
  --worker_number 32 \
  --round 20 \
  --epoch 1 \
  --learning_rate 0.1 \
  --batch_size 16 \
  --n_train 1024 \
  --n_test 512 \
  --log_level INFO \
  --sweep_seeds 0,1,2,3,4,5,6,7 \
  --sweep_dir "$SWEEP_DIR"

python scripts/report_run.py "$SWEEP_DIR"
