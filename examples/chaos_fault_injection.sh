#!/bin/bash
# Failure injection + quorum-guarded rounds (docs/ROBUSTNESS.md): 20% of
# each round's cohort uploads all-NaN parameters (round-correlated: bad
# rounds cluster), the coordinate-median absorbs them, and any round whose
# honest survivors fall below the quorum floor — or whose aggregate went
# non-finite — is REJECTED in-program (previous global retained;
# rounds_rejected / survivor_count land in every metrics record).
# CRC-verified checkpoints every 5 rounds, newest 3 kept; on SIGTERM the
# run finishes its in-flight round, checkpoints, and exits cleanly.
# Crash-resume bit-exactness proof: python scripts/chaos_resume.py
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name cifar10 --model_name cnn_tpu \
  --distributed_algorithm fed \
  --worker_number 100 --round 30 --epoch 1 --learning_rate 0.1 \
  --momentum 0.9 --batch_size 25 --participation_fraction 0.5 \
  --failure_mode corrupt_nan --failure_prob 0.2 --failure_correlation 0.5 \
  --aggregation median --min_survivors 25 \
  --checkpoint_dir ckpt_chaos --checkpoint_every 5 --checkpoint_keep_last 3 \
  --log_level INFO
