#!/bin/bash
# Multi-host (DCN) bring-up demo on plain CPU: two processes join one
# jax.distributed job over localhost, after which jax.devices() spans both
# processes and the ordinary mesh/sharding code runs the client axis
# across them (on a TPU pod, just pass --multihost true and let the
# environment auto-configure; the explicit flags below are for non-TPU
# clusters and CI). Each process must see the same worker_number and a
# mesh over the GLOBAL device count.
#
# The python -c wrapper pins the CPU platform via jax.config BEFORE any
# backend initialization: JAX_PLATFORMS alone loses to force-registered
# accelerator plugins (and an accelerator plugin may bring its own
# pre-initialized distributed runtime, which would make this demo a no-op).
set -e
PORT=${PORT:-8476}

run() {
  python -c "
import jax
jax.config.update('jax_platforms', 'cpu')
from distributed_learning_simulator_tpu.simulator import main
main()
" \
    --dataset_name synthetic --model_name mlp --distributed_algorithm fed \
    --worker_number 8 --round 3 --epoch 1 --learning_rate 0.1 \
    --multihost true --coordinator_address "127.0.0.1:$PORT" \
    --num_processes 2 --process_id "$1" \
    --mesh_devices 2 --log_level INFO \
    "${@:2}"
}

run 0 &
PID0=$!
run 1
wait $PID0

# The same topology with the DISTRIBUTED SHARD STORE (ISSUE 15): each
# process owns half the clients and serves its members of every round's
# owner-permuted cohort into its addressable mesh shards — streamed
# million-client residency composed with multi-process scale. Requires
# the hashed O(cohort) sampler (every host replays the full cohort per
# round); metrics gain the schema-v11 multihost sub-object.
PORT=$((PORT + 1))
run 0 \
  --client_residency streamed --participation_fraction 0.5 \
  --participation_sampler hashed &
PID0=$!
run 1 \
  --client_residency streamed --participation_fraction 0.5 \
  --participation_sampler hashed
wait $PID0
