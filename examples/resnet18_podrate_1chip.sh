#!/bin/bash
# The flagship single-chip configuration: 1000 FedAvg clients x ResNet-18
# at >= the full v5e-8 pod-rate target (333.3 clients*rounds/s) on ONE
# chip. The enabling knob is --local_compute_dtype bfloat16: per-client
# diverged params/grads/momenta live in bf16 with hash-dither stochastic
# rounding (accuracy parity with f32 — mechanism and negative results in
# docs/PERFORMANCE.md), halving the round's dominant HBM traffic.
# Measured: 439.5 clients*rounds/s = 1.32x the pod-rate (driver bench
# incl. per-round eval, round 5; 448-450 on the eval-free profile
# harness. W-folded stage 1 + folded stem + closed-form GroupNorm
# backward; 438.6-440 in round 4, 385 in round 3).
# Accuracy-bearing runs: see resnet18_converge_1chip.sh.
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name cifar10 --model_name resnet18 \
  --distributed_algorithm fed \
  --worker_number 1000 --round 50 --epoch 1 --learning_rate 0.1 \
  --momentum 0.9 --batch_size 25 \
  --client_chunk_size 40 --local_compute_dtype bfloat16 \
  --eval_batch_size 10000 --log_level INFO
