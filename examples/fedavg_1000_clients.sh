#!/bin/bash
# Scale config: 1000 FedAvg clients on CIFAR-10 (BASELINE.json north star).
# One jitted round trains all 1000 clients (chunked 250 at a time to bound
# HBM) and aggregates with a fused weighted sum; ~0.13s/round on one chip
# with the MXU-aligned CNN.
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name cifar10 --model_name cnn_tpu \
  --distributed_algorithm fed \
  --worker_number 1000 --round 50 --epoch 1 --learning_rate 0.1 \
  --momentum 0.9 --batch_size 25 --client_chunk_size 250 \
  --eval_batch_size 10000 --log_level INFO
