#!/bin/bash
# Canonical smoke run, parity with the reference launch script
# (reference simulator.sh:1): MNIST + LeNet-5 + exact multi-round Shapley,
# 4 workers, 2 local epochs, 10 rounds, lr 0.01.
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name mnist --model_name lenet5 \
  --distributed_algorithm multiround_shapley_value \
  --worker_number 4 --epoch 2 --round 10 --learning_rate 0.01 \
  --log_level INFO
# Commented variant, parity with reference simulator.sh:2:
# python -m distributed_learning_simulator_tpu.simulator \
#   --dataset_name mnist --model_name lenet5 \
#   --distributed_algorithm sign_SGD \
#   --worker_number 4 --epoch 2 --round 1 --learning_rate 0.01
