#!/bin/bash
# GTG-Shapley Monte-Carlo contribution scoring: permutation sampling with
# guided truncation; per-round Shapley values logged and subset metrics
# pickled to the run's artifact dir.
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name mnist --model_name lenet5 \
  --distributed_algorithm GTG_shapley_value \
  --worker_number 8 --round 5 --epoch 1 --learning_rate 0.1 \
  --round_trunc_threshold 0.01 --log_level INFO
