#!/bin/bash
# GTG-Shapley Monte-Carlo contribution scoring: permutation sampling with
# guided truncation; per-round Shapley values logged and subset metrics
# pickled to the run's artifact dir. At large N add
# --shapley_eval_samples (subset utilities on a test subsample) and
# --shapley_eval_chunk (amortize the client-stack read across more
# subsets per batched call). N=1000 cnn_tpu operating points (round 5,
# docs/PERFORMANCE.md § Scale validation; the evaluator reads the
# client stack in bf16 by default — measured fidelity-free):
#   default auto permutation cap max(500, 2N): CONVERGED estimates at
#     1149-1719 permutations, 264-269 s/round (--shapley_eval_samples
#     1000 --shapley_eval_chunk 128)
#   fixed 1000-permutation budget: 90.3 s/round at the same knobs, or
#     ~168 s/round at --shapley_eval_samples 2000 (r4-equal fidelity)
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name mnist --model_name lenet5 \
  --distributed_algorithm GTG_shapley_value \
  --worker_number 8 --round 5 --epoch 1 --learning_rate 0.1 \
  --round_trunc_threshold 0.01 --log_level INFO
