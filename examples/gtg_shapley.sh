#!/bin/bash
# GTG-Shapley Monte-Carlo contribution scoring: permutation sampling with
# guided truncation; per-round Shapley values logged and subset metrics
# pickled to the run's artifact dir. At large N add
# --shapley_eval_samples 2000 (subset utilities on a test subsample) and
# --shapley_eval_chunk 64 (amortize the client-stack read across more
# subsets per batched call): N=1000 cnn_tpu measures 173 s/round
# (docs/PERFORMANCE.md § Scale validation).
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name mnist --model_name lenet5 \
  --distributed_algorithm GTG_shapley_value \
  --worker_number 8 --round 5 --epoch 1 --learning_rate 0.1 \
  --round_trunc_threshold 0.01 --log_level INFO
