#!/bin/bash
# SignSGD majority vote (reference simulator.sh:2 variant): per-optimizer-step
# sign-compressed all-reduce — 1-bit uplink, elementwise majority vote,
# manual SGD apply. Requires the SGD optimizer. Note the small learning
# rate: every step moves every parameter by exactly +/-lr, so SignSGD wants
# lr ~10x below plain SGD's (0.001 here reaches ~0.97 in 5 rounds).
# At flagship scale (1000 clients x ResNet-18, lr 0.005): 368 c*r/s
# (1.10x pod-rate, the per-step vote is the system's highest-frequency
# sync) and 0.6486@150 rounds still climbing — the 1-bit vote's genuine
# convergence cost (docs/PERFORMANCE.md round 5).
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name mnist --model_name lenet5 \
  --distributed_algorithm sign_SGD \
  --worker_number 4 --round 5 --epoch 1 --learning_rate 0.001 \
  --log_level INFO
