#!/bin/bash
# Poisoning defense demo: the heterogeneity experiment (reference
# simulator_backup.py swaps worker 0's training data) combined with the
# Byzantine-robust coordinate-median aggregator this framework adds.
# Compare the accuracy trajectory with and without --aggregation median.
python -m distributed_learning_simulator_tpu.simulator_heterogeneous \
  --dataset_name cifar10 --model_name cnn_tpu \
  --distributed_algorithm fed \
  --worker_number 8 --round 10 --epoch 1 --learning_rate 0.1 \
  --aggregation median --log_level INFO
