#!/bin/bash
# Scale config BASELINE.json configs[4]: 1000 clients, non-IID
# Dirichlet(alpha=0.1), ResNet-18 (GroupNorm, bf16). Shards are padded to
# --max_shard_size with 0/1 masks (empty clients get zero aggregation
# weight). Size-aware work scheduling (config.bucket_client_work, on by
# default) sorts clients by shard size and scans each chunk only as far as
# its largest member — with the folded stem + closed-form GroupNorm
# backward, 2.55 s/round (392-393 clients*rounds/s, 1.18x pod-rate) on
# one chip at shard cap 100 with chunk 40, vs 5.01 s/round in round 3.
# Round-5 converged rerun: 0.8132 final accuracy over 150 rounds at a
# sustained 391.7 c*r/s; a 3-seed ON/OFF study shows the scheduler is
# accuracy-neutral (docs/PERFORMANCE.md).
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name cifar10 --model_name resnet18 \
  --distributed_algorithm fed \
  --worker_number 1000 --round 20 --epoch 1 --learning_rate 0.02 \
  --momentum 0.9 --batch_size 25 \
  --partition dirichlet --dirichlet_alpha 0.1 --max_shard_size 100 \
  --client_chunk_size 40 --local_compute_dtype bfloat16 \
  --eval_batch_size 10000 --log_level INFO
