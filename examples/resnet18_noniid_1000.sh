#!/bin/bash
# Scale config BASELINE.json configs[4]: 1000 clients, non-IID
# Dirichlet(alpha=0.1), ResNet-18 (GroupNorm, bf16). Shards are padded to
# --max_shard_size with 0/1 masks (empty clients get zero aggregation
# weight), and --client_chunk_size 50 bounds the per-chunk HBM footprint
# (~6.3 s/round on one chip at shard cap 100 — every client scans
# cap/batch_size steps; chunk 200 OOMs — see docs/PERFORMANCE.md).
python -m distributed_learning_simulator_tpu.simulator \
  --dataset_name cifar10 --model_name resnet18 \
  --distributed_algorithm fed \
  --worker_number 1000 --round 20 --epoch 1 --learning_rate 0.1 \
  --momentum 0.9 --batch_size 25 \
  --partition dirichlet --dirichlet_alpha 0.1 --max_shard_size 100 \
  --client_chunk_size 50 --eval_batch_size 10000 --log_level INFO
