#!/bin/bash
# Cross-host distributed tracing end-to-end on plain CPU: a 2-process
# federation with span_trace on (host 1 deliberately slowed at every
# spill-exchange barrier via DLS_STRAGGLE_S), then the stitcher merges
# the per-host span journals into ONE timeline — per-round barrier skew
# with the straggling host named, per-host DCN-wait vs compute split,
# and a perfetto-loadable Chrome trace (open trace.json at
# https://ui.perfetto.dev). span_trace='off' (the default) compiles the
# exact pre-feature program; the bench gate bounds the 'on' overhead at
# 5% (scripts/compare_bench.py --span-overhead-threshold).
#
# The python -c wrapper pins the CPU platform via jax.config BEFORE any
# backend initialization (JAX_PLATFORMS alone loses to force-registered
# accelerator plugins).
set -e
PORT=${PORT:-8478}
OUT=${OUT:-/tmp/dls_trace_demo}
rm -rf "$OUT"
mkdir -p "$OUT/spans"

run() {
  python -c "
import jax
jax.config.update('jax_platforms', 'cpu')
from distributed_learning_simulator_tpu.simulator import main
main()
" \
    --dataset_name synthetic --model_name mlp --distributed_algorithm fed \
    --worker_number 8 --round 3 --epoch 1 --learning_rate 0.1 \
    --multihost true --coordinator_address "127.0.0.1:$PORT" \
    --num_processes 2 --process_id "$1" \
    --mesh_devices 2 --log_level INFO \
    --client_residency streamed --participation_fraction 0.5 \
    --participation_sampler hashed \
    --span_trace on --span_dir "$OUT/spans" --log_root "$OUT" \
    "${@:2}"
}

# Host 0 runs clean; host 1 sleeps 200 ms before every spill barrier —
# the stitched timeline must attribute the skew to host 1.
run 0 &
PID0=$!
DLS_STRAGGLE_S=0.2 run 1
wait $PID0

echo
echo "== stitched cross-host timeline =="
python scripts/trace_timeline.py "$OUT/spans" --out "$OUT/trace.json"
echo
echo "Chrome trace written to $OUT/trace.json (load in ui.perfetto.dev)"

# The run report composes the same stitcher: v12 span rollup from the
# primary's metrics.jsonl + the cross-host section from the journals.
METRICS=$(find "$OUT" -name metrics.jsonl | head -1)
if [ -n "$METRICS" ]; then
  echo
  echo "== report_run =="
  python scripts/report_run.py "$METRICS" --spans "$OUT/spans"
fi
