"""Multi-seed accuracy evidence for size-aware work scheduling.

VERDICT r4 weak #3: the converged named-config comparison (non-IID
Dirichlet(0.1), 1000 clients, ResNet-18) showed scheduling ON at 0.8142 vs
OFF at 0.7800 on ONE seed, attributed to reshuffle-class batch-composition
noise without variance evidence. This script runs the same scale at a
cheaper horizon over several seeds, scheduling ON and OFF, so the claim
carries a spread: either the ON/OFF bands overlap (scheduling is
accuracy-neutral at this config) or they don't (the schedule shifts
convergence and the docs must say so).

The seed drives the Dirichlet split, model init, and training RNG — ON and
OFF at the same seed train on identical data from identical inits; only
batch composition (which samples share a step's masked slots) differs.

Usage: python scripts/measure_bucketed_seeds.py [rounds] [seeds...]
(defaults: 50 rounds, seeds 0 1 2)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 50
    seeds = [int(s) for s in sys.argv[2:]] or [0, 1, 2]

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    results = {}
    for seed in seeds:
        for sched in (True, False):
            config = ExperimentConfig(
                dataset_name="cifar10", model_name="resnet18",
                distributed_algorithm="fed", worker_number=1000,
                round=rounds, epoch=1, learning_rate=0.02, momentum=0.9,
                batch_size=25, partition="dirichlet", dirichlet_alpha=0.1,
                max_shard_size=100, client_chunk_size=40,
                local_compute_dtype="bfloat16", eval_batch_size=10000,
                lr_schedule="cosine", lr_min_factor=0.1,
                bucket_client_work=sched, seed=seed, log_level="WARNING",
            )
            t0 = time.perf_counter()
            res = run_simulation(config, setup_logging=False)
            wall = time.perf_counter() - t0
            accs = [h["test_accuracy"] for h in res["history"]]
            key = f"seed{seed}_{'on' if sched else 'off'}"
            results[key] = {
                "final_accuracy": accs[-1],
                "last5_mean": sum(accs[-5:]) / len(accs[-5:]),
                "wall_s": round(wall, 1),
                "round_s": round(
                    sum(h["round_seconds"] for h in res["history"][1:])
                    / max(len(accs) - 1, 1), 3,
                ),
            }
            print(key, json.dumps(results[key]), flush=True)
    on = [v["final_accuracy"] for k, v in results.items() if k.endswith("_on")]
    off = [v["final_accuracy"] for k, v in results.items() if k.endswith("_off")]
    mean = lambda xs: sum(xs) / len(xs)  # noqa: E731
    spread = lambda xs: max(xs) - min(xs)  # noqa: E731
    print(json.dumps({
        "rounds": rounds, "seeds": seeds,
        "on_final": on, "off_final": off,
        "on_mean": round(mean(on), 4), "off_mean": round(mean(off), 4),
        "on_spread": round(spread(on), 4), "off_spread": round(spread(off), 4),
    }))


if __name__ == "__main__":
    main()
