"""Experiment: where the flagship per-client ResNet fwd+bwd time lives.

Builds truncated ResNet-18 variants (stem only, +stage1, +stage2, ...) and
times one vmapped per-client fwd+bwd step (chunk clients x batch) for each;
successive differences attribute time to stages. Cross-checks the
single-layer microbench (exp_client_conv.py) against in-context cost.

Usage: python scripts/exp_resnet_stages.py [n_chain] [chunk] [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import flax.linen as nn
import jax
import jax.numpy as jnp

from distributed_learning_simulator_tpu.models.resnet import ResidualBlock


class TruncatedResNet(nn.Module):
    n_stages: int
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=32, dtype=self.dtype)(x)
        x = nn.relu(x)
        for stage in range(self.n_stages):
            features = self.width * (2 ** stage)
            for block in range(2):
                strides = 2 if stage > 0 and block == 0 else 1
                x = ResidualBlock(features, strides, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(10, dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


def timeit(fn, args, n):
    out = fn(*args)
    jax.device_get(out)
    t0 = time.perf_counter()
    acc = out
    for _ in range(n):
        acc = acc + fn(*args)
    jax.device_get(acc)
    return (time.perf_counter() - t0) / n


def main():
    n_chain = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 25

    key = jax.random.key(0)
    x = jax.random.normal(key, (chunk, batch, 32, 32, 3), jnp.float32)
    y = jax.random.randint(jax.random.fold_in(key, 1), (chunk, batch), 0, 10)

    prev = 0.0
    for n_stages in range(5):
        model = TruncatedResNet(n_stages=n_stages)
        params = model.init(jax.random.fold_in(key, 2), x[0])["params"]
        # One weight set per client.
        cparams = jax.vmap(lambda i: jax.tree_util.tree_map(
            lambda p: p + 0.0 * i, params))(jnp.arange(chunk, dtype=jnp.float32))

        def loss(p, xc, yc):
            logits = model.apply({"params": p}, xc)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(
                jnp.take_along_axis(logp, yc[:, None], axis=1)
            )

        def step(cp, x_, y_):
            l, gr = jax.vmap(jax.value_and_grad(loss))(cp, x_, y_)
            return jnp.sum(l) + sum(
                jnp.sum(g.astype(jnp.float32))
                for g in jax.tree_util.tree_leaves(gr)
            )

        t = timeit(jax.jit(step), (cparams, x, y), n_chain)
        print(
            f"stem+{n_stages} stages: {t*1e3:7.2f} ms/step "
            f"(delta {1e3*(t-prev):+7.2f} ms)"
        )
        prev = t


if __name__ == "__main__":
    main()
