"""Aggregate a saved device trace by program category (stage attribution).

Usage: python scripts/trace_categories.py <trace_dir> [top_n] [category...]

Buckets ops by shape signatures in ``long_name`` (ResNet-18 stage maps at
the flagship chunk-40 config), so a round's device time reads as a stage
budget instead of 3000 instance rows. Pure-CPU parse of an existing trace.

Thin CLI wrapper since ISSUE 8: the rule table and the categorizer are
the tested public API in ``utils/tracing`` (``STAGE_RULES``,
``categorize_long_name``, ``categorize_ops``) — the cost model
(telemetry/costmodel.py) consumes the same ledger machinery with its
generic op-class rules, so the selection rule (wrapper ``while``/``jit(``
frames excluded) lives in exactly one place.
"""

from __future__ import annotations

import os
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_learning_simulator_tpu.utils.tracing import (
    STAGE_RULES,
    categorize_long_name,
    categorize_ops,
    iter_device_ops,
)

# Backwards-compatible aliases (pre-ISSUE-8 importers of this script).
RULES = STAGE_RULES
categorize = categorize_long_name


def main():
    trace_dir = sys.argv[1]
    top = int(sys.argv[2]) if len(sys.argv) > 2 else 15
    cats = categorize_ops(trace_dir, rules=STAGE_RULES)
    total = sum(e["device_ms"] for e in cats.values())
    print(f"total device op time: {total:.1f} ms")
    print(f"{'category':12s} {'ms':>9s} {'GB':>9s} {'GB/s':>7s} {'n':>6s}")
    for cat, e in sorted(cats.items(), key=lambda kv: -kv[1]["device_ms"]):
        gbps = (
            e["bytes_gb"] / (e["device_ms"] / 1e3)
            if e["device_ms"] else 0.0
        )
        print(f"{cat:12s} {e['device_ms']:9.1f} {e['bytes_gb']:9.2f} "
              f"{gbps:7.0f} {e['op_count']:6d}")
    wanted = sys.argv[3:]
    if not wanted:
        return
    # Per-op detail rows only when asked: a second gzip pass, keyed the
    # way the original script printed them.
    ops = defaultdict(lambda: [0.0, 0.0, 0])
    for ev in iter_device_ops(trace_dir):
        args = ev.get("args") or {}
        ln = args.get("long_name", "")
        cat = categorize_long_name(ln)
        key = (cat, ev.get("name", "?").split(".")[0], ln[:100])
        ops[key][0] += float(ev.get("dur", 0.0))
        ops[key][1] += float(args.get("raw_bytes_accessed", 0) or 0)
        ops[key][2] += 1
    for want in wanted:
        print(f"\n--- top ops in {want} ---")
        rows = sorted(
            ((k, v) for k, v in ops.items() if k[0] == want),
            key=lambda kv: -kv[1][0],
        )[:top]
        for (cat, fam, ln), (dur, byt, cnt) in rows:
            gbps = (byt / 2**30) / (dur / 1e6) if dur else 0.0
            print(f"{dur / 1e3:8.1f}ms {byt / 2**30:7.2f}GB {gbps:5.0f}GB/s "
                  f"x{cnt:<4d} {fam} {ln}")


if __name__ == "__main__":
    main()
