"""Aggregate a saved device trace by program category (stage attribution).

Usage: python scripts/trace_categories.py <trace_dir> [top_n]

Buckets ops by shape signatures in ``long_name`` (ResNet-18 stage maps at
the flagship chunk-40 config), so a round's device time reads as a stage
budget instead of 3000 instance rows. Pure-CPU parse of an existing trace.
"""

from __future__ import annotations

import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_learning_simulator_tpu.utils.tracing import iter_device_ops

RULES = [
    ("s4_wgrad", r"3,3,512,512.*fusion\(|fusion.*= f32\[3,3,512,512\]"),
    ("s3_wgrad", r"= f32\[3,3,256,256\]"),
    ("s2_wgrad", r"= f32\[3,3,128,128\]"),
    ("s1_wgrad", r"= f32\[3,3,128,40,128\]|= f32\[3,4,3,40,128\]|= f32\[3,2,128,40,"),
    ("stage4", r"4,4,512|2,2,512"),
    ("stage3", r"8,8,256"),
    ("stage2", r"16,16,128"),
    # stage-1 folded activations: NHWC [.., 32, 16, 128] (rounds 3-4) or
    # HWNC [32, 16, .., 128] (round 5); packed kernels/grads either way.
    ("stage1f", r"32,16,128|32,16,40,25,128|32,16,1000,128"
                r"|3,3,128,40,128|3,4,3,40,128"),
    ("dense/head", r"512,10|,10\]"),
    ("decode", r"u8\[|s32\["),
]


def categorize(long_name: str) -> str:
    for name, pat in RULES:
        if re.search(pat, long_name):
            return name
    return "other"


def main():
    trace_dir = sys.argv[1]
    top = int(sys.argv[2]) if len(sys.argv) > 2 else 15
    cats = defaultdict(lambda: [0.0, 0.0, 0])
    ops = defaultdict(lambda: [0.0, 0.0, 0])
    total = 0.0
    for ev in iter_device_ops(trace_dir):
        args = ev.get("args") or {}
        ln = args.get("long_name", "")
        dur = float(ev.get("dur", 0.0))
        byt = float(args.get("raw_bytes_accessed", 0) or 0)
        cat = categorize(ln)
        for store in (cats[cat], ops[(cat, ev.get("name", "?").split(".")[0], ln[:100])]):
            store[0] += dur
            store[1] += byt
            store[2] += 1
        total += dur
    print(f"total device op time: {total / 1e3:.1f} ms")
    print(f"{'category':12s} {'ms':>9s} {'GB':>9s} {'GB/s':>7s} {'n':>6s}")
    for cat, (dur, byt, cnt) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        gbps = (byt / 2**30) / (dur / 1e6) if dur else 0.0
        print(f"{cat:12s} {dur / 1e3:9.1f} {byt / 2**30:9.2f} {gbps:7.0f} {cnt:6d}")
    for want in sys.argv[3:]:
        print(f"\n--- top ops in {want} ---")
        rows = sorted(
            ((k, v) for k, v in ops.items() if k[0] == want),
            key=lambda kv: -kv[1][0],
        )[:top]
        for (cat, fam, ln), (dur, byt, cnt) in rows:
            gbps = (byt / 2**30) / (dur / 1e6) if dur else 0.0
            print(f"{dur / 1e3:8.1f}ms {byt / 2**30:7.2f}GB {gbps:5.0f}GB/s "
                  f"x{cnt:<4d} {fam} {ln}")


if __name__ == "__main__":
    main()
