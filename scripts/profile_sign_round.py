"""Device-trace profile of one round at ResNet scale (any algorithm).

Round-3 method (docs/PERFORMANCE.md): jax.profiler works through the
tunnel; the device lane events in vm.trace.json.gz carry per-op ``dur``
and ``raw_bytes_accessed``, which is the only reliable attribution of
round time (isolated microbenches lie — measured round 3).

Usage: python scripts/profile_sign_round.py [chunk] [trace_dir] [algo] [dtype]
(algo default sign_SGD; dtype default float32 — use bfloat16 for the fed
flagship configuration.)
"""

from __future__ import annotations

import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp


def build_round(chunk: int, algo: str = "sign_SGD", dtype: str = "float32"):
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.factory import get_algorithm
    from distributed_learning_simulator_tpu.models.registry import (
        get_model,
        init_params,
    )
    from distributed_learning_simulator_tpu.parallel.engine import (
        make_decoder,
        make_eval_fn,
        make_optimizer,
    )
    from distributed_learning_simulator_tpu.simulator import build_client_data

    momentum = 0.0 if algo == "sign_SGD" else 0.9
    config = ExperimentConfig(
        dataset_name="cifar10", model_name="resnet18",
        distributed_algorithm=algo, worker_number=1000, round=3,
        epoch=1, learning_rate=0.01, momentum=momentum, batch_size=25,
        log_level="WARNING", client_chunk_size=chunk,
        local_compute_dtype=dtype,
    )
    dataset = get_dataset(config.dataset_name, seed=0)
    client_data = build_client_data(config, dataset)
    model = get_model(config.model_name, num_classes=dataset.num_classes)
    params = init_params(model, dataset.x_train[:1], seed=0)
    optimizer = make_optimizer("SGD", config.learning_rate,
                               momentum=momentum)
    algorithm = get_algorithm(algo, config)
    algorithm.prepare(model.apply, make_eval_fn(model.apply))
    round_fn = algorithm.make_round_fn(
        model.apply, optimizer, client_data.n_clients,
        preprocess=make_decoder(client_data.sample_shape),
        client_sizes=client_data.sizes,
    )
    round_jit = jax.jit(round_fn)
    operands = (
        params, None, jnp.asarray(client_data.x),
        jnp.asarray(client_data.y), jnp.asarray(client_data.mask),
        jnp.asarray(client_data.sizes),
    )
    return round_jit, operands


def parse_trace(trace_dir: str, top: int = 30):
    from distributed_learning_simulator_tpu.utils.tracing import (
        iter_device_ops,
    )

    # Group by (hlo op family, shape prefix): instance ids collapse so the
    # per-(op, shape) totals attribute round time by program structure.
    by_op: dict[tuple, list[float]] = defaultdict(lambda: [0.0, 0.0, 0])
    total = 0.0
    for ev in iter_device_ops(trace_dir):
        args = ev.get("args") or {}
        dur = float(ev.get("dur", 0.0))  # us
        fam = ev.get("name", "?").split(".")[0]
        key = (fam, args.get("long_name", "")[:90])
        rec = by_op[key]
        rec[0] += dur
        rec[1] += float(args.get("raw_bytes_accessed", 0) or 0)
        rec[2] += 1
        total += dur
    rows = sorted(by_op.items(), key=lambda kv: -kv[1][0])[:top]
    print(f"total device op time: {total / 1e3:.1f} ms")
    print(f"{'op':82s} {'ms':>9s} {'GB':>8s} {'GB/s':>7s} {'n':>6s}")
    for (fam, ln), (dur, byt, cnt) in rows:
        gbps = (byt / 2**30) / (dur / 1e6) if dur else 0.0
        label = f"{fam} {ln}"[:82]
        print(f"{label:82s} {dur / 1e3:9.1f} {byt / 2**30:8.2f} "
              f"{gbps:7.0f} {cnt:6d}")
    return total


def main():
    chunk = int(sys.argv[1]) if len(sys.argv) > 1 else 40
    trace_dir = sys.argv[2] if len(sys.argv) > 2 else "/tmp/sign_trace"
    algo = sys.argv[3] if len(sys.argv) > 3 else "sign_SGD"
    dtype = sys.argv[4] if len(sys.argv) > 4 else "float32"
    round_jit, operands = build_round(chunk, algo, dtype)
    key = jax.random.key(1)

    t0 = time.perf_counter()
    g, st, aux = round_jit(*operands, jax.random.fold_in(key, 0))
    jax.device_get(aux["mean_client_loss"])
    print(f"compile+first round: {time.perf_counter() - t0:.1f}s")

    t0 = time.perf_counter()
    for i in range(1, 4):
        g, st, aux = round_jit(
            operands[0], st, *operands[2:], jax.random.fold_in(key, i)
        )
    jax.device_get(aux["mean_client_loss"])
    per_round = (time.perf_counter() - t0) / 3
    print(f"steady state: {per_round * 1000:.0f} ms/round "
          f"({1000 / per_round:.0f} c*r/s)")

    jax.profiler.start_trace(trace_dir)
    g, st, aux = round_jit(
        operands[0], st, *operands[2:], jax.random.fold_in(key, 9)
    )
    jax.device_get(aux["mean_client_loss"])
    jax.profiler.stop_trace()
    parse_trace(trace_dir)


if __name__ == "__main__":
    main()
