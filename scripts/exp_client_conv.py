"""Experiment: per-client conv formulations for the flagship ResNet round.

The flagship round's dominant cost is the per-client conv fwd+bwd: under
vmap every client carries its own weight set, so XLA lowers each conv to a
grouped conv / small batched GEMM (docs/PERFORMANCE.md "Remaining ceiling
analysis": 45-70 GB/s effective on those shapes). This script measures, per
ResNet-18 stage shape, a single conv layer's fwd+bwd under three
formulations:

  A. vmap(lax.conv_general_dilated) over clients — what flax+vmap produce
     today (the baseline the round program runs).
  B. explicit im2col: conv_general_dilated_patches once per client batch,
     then one batched GEMM ('cmk,cko->cmo') — fwd AND both backward
     contractions become MXU-aligned batched GEMMs.
  C. B, with the patches precomputed OUTSIDE the grad (activation-style
     reuse; bounds what fusing patch extraction would buy).

Timing: chain N dispatches, fetch ONE scalar (the tunnel fetch costs
~100 ms; block_until_ready returns early under this plugin).

Usage: python scripts/exp_client_conv.py [n_chain] [chunk] [batch]
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

STAGES = [
    ("stage1", 32, 64, 64),
    ("stage2", 16, 128, 128),
    ("stage3", 8, 256, 256),
    ("stage4", 4, 512, 512),
]


def timeit(fn, args, n):
    out = fn(*args)
    jax.device_get(out)  # compile + settle
    t0 = time.perf_counter()
    acc = out
    for _ in range(n):
        acc = acc + fn(*args)
    jax.device_get(acc)
    return (time.perf_counter() - t0) / n


def main():
    n_chain = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 25

    key = jax.random.key(0)
    for name, hw, cin, cout in STAGES:
        kx, kw, kg = jax.random.split(jax.random.fold_in(key, hw), 3)
        x = jax.random.normal(kx, (chunk, batch, hw, hw, cin), jnp.bfloat16)
        w = jax.random.normal(kw, (chunk, 3, 3, cin, cout), jnp.bfloat16)
        # Fixed cotangent so bwd cost is measured without a real loss.
        g = jax.random.normal(kg, (chunk, batch, hw, hw, cout), jnp.bfloat16)

        # --- A: vmapped conv ------------------------------------------------
        def conv_one(xc, wc):
            return jax.lax.conv_general_dilated(
                xc, wc, window_strides=(1, 1), padding="SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )

        def loss_a(w_, x_):
            y = jax.vmap(conv_one)(x_, w_)
            return jnp.sum((y * g).astype(jnp.float32))

        f_a = jax.jit(jax.grad(loss_a, argnums=(0, 1)))

        def run_a(w_, x_):
            gw, gx = f_a(w_, x_)
            return jnp.sum(gw.astype(jnp.float32)) + jnp.sum(
                gx.astype(jnp.float32)
            )

        t_a = timeit(jax.jit(run_a), (w, x), n_chain)

        # --- B: im2col + batched GEMM --------------------------------------
        def patches_one(xc):
            # [B, H, W, 9*cin] patch tensor for one client's batch.
            return jax.lax.conv_general_dilated_patches(
                xc, (3, 3), (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )

        def loss_b(w_, x_):
            p = jax.vmap(patches_one)(x_)  # [C, B, H, W, 9cin]
            p = p.reshape(chunk, batch * hw * hw, 9 * cin)
            wmat = w_.transpose(0, 3, 1, 2, 4).reshape(chunk, 9 * cin, cout)
            y = jnp.einsum(
                "cmk,cko->cmo", p, wmat,
                preferred_element_type=jnp.float32,
            ).astype(jnp.bfloat16)
            gm = g.reshape(chunk, batch * hw * hw, cout)
            return jnp.sum((y * gm).astype(jnp.float32))

        f_b = jax.jit(jax.grad(loss_b, argnums=(0, 1)))

        def run_b(w_, x_):
            gw, gx = f_b(w_, x_)
            return jnp.sum(gw.astype(jnp.float32)) + jnp.sum(
                gx.astype(jnp.float32)
            )

        t_b = timeit(jax.jit(run_b), (w, x), n_chain)

        # --- C: weight-grad GEMM only, patches given ------------------------
        p_pre = jax.jit(
            lambda x_: jax.vmap(patches_one)(x_).reshape(
                chunk, batch * hw * hw, 9 * cin
            )
        )(x)
        gm = g.reshape(chunk, batch * hw * hw, cout)

        def wgrad_only(p_, g_):
            gw = jnp.einsum(
                "cmk,cmo->cko", p_, g_,
                preferred_element_type=jnp.float32,
            )
            return jnp.sum(gw)

        t_c = timeit(jax.jit(wgrad_only), (p_pre, gm), n_chain)

        # Traffic estimate for A's fwd+bwd (bf16): x and g read ~2-3x, w
        # negligible.
        mb = (x.size + g.size) * 2 / 2**20
        print(
            f"{name}: vmap-conv {t_a*1e3:7.2f} ms | im2col-gemm "
            f"{t_b*1e3:7.2f} ms | wgrad-gemm-only {t_c*1e3:7.2f} ms "
            f"| act+cot {mb:.0f} MB"
        )


if __name__ == "__main__":
    main()
