"""Offline run reporter: render a run's artifacts dir into a summary.

    python scripts/report_run.py <artifacts_dir | metrics.jsonl>
        [--trace DIR] [--json OUT.json] [--top K]

Input is the per-run artifacts directory the simulator writes
(``log/<algo>/<dataset>/<model>/<run-id>_artifacts`` containing
``metrics.jsonl``) or a ``metrics.jsonl`` path directly. Renders a
terminal summary — accuracy curve, per-round phase-time breakdown,
compile events, rejected rounds, peak HBM, and (schema v3) a
client-health section: the anomaly-flag table, a divergence timeline
over the per-round update-norm spread, and per-client loss sparklines
when the records carry raw per-client values (cohorts up to the
per-client cap; telemetry/client_stats.py). Optionally writes the same
content as machine-readable JSON (``--json``). ``--trace`` points at a
``jax.profiler`` trace directory (``config.profile_dir``) and adds the
deterministic device-op totals plus top-ops-by-bytes AND
top-ops-by-time tables (same selection rule as bench.py's regression
proxy: utils/tracing.py).

Reads all metrics schemas: v1 (pre-telemetry; accuracy/timing only), v2
(``telemetry`` sub-object), v3 (``client_stats`` sub-object), v4
(``async`` sub-object — rendered as the staleness section:
buffer-occupancy timeline, staleness histogram, simulated-clock speedup
vs sync; see docs/OBSERVABILITY.md), v5 (``stream`` sub-object —
rendered as an h2d transfer row under the phase table plus run-total
transfer accounting; client_residency='streamed',
docs/PERFORMANCE.md § Streamed client state), v6 (``costmodel``
sub-object — rendered as the "cost at scale" section: the roofline
model's predicted round time, bottleneck, and $/run across the
topology table, with this run's measured round as the anchor row;
telemetry/costmodel.py). ``--trace`` computes the same section LIVE
from the trace's categorized ledger when the records don't carry one
(``--cost-rounds`` sets the $/run horizon), and v7 (``valuation``
sub-object — rendered as the client-valuation section: latest
top-k/bottom-k client tables, the loss-delta curve, the
flagged-client overlay against the v3 client-health section, and the
latest GTG audit-correlation line; telemetry/valuation.py), and v8
(``sweep`` sub-object — rendered as the sweep section: per-point
accuracy table, winner line, compile-reuse summary, and — when a trace
is attached (``--trace``) — the cost model's $/sweep row per topology;
sweep/engine.py), and v9 (``population`` sub-object — rendered as the
dynamic-population section: alive-N-over-time sparkline, per-round
join/depart counts, churn-rejected rounds, and the planted
drift-cohort overlay against the v7 valuation top/bottom tables;
robustness/population.py), and v10 (``gtg`` sub-object — the
mesh-sharded GTG walk's per-round provenance; its audit-side face,
wall seconds + device count, rides the v7 valuation audit line;
algorithms/shapley.py), and v11 (``multihost`` sub-object — the
distributed shard store's per-host assembly provenance;
parallel/streaming.py), and v12 (``spans`` sub-object — rendered as
the distributed-trace section: per-round span counts, DCN wait vs
transfer split, and the barrier-skew timeline; telemetry/spans.py).
When ``spans_*.jsonl`` journals sit next to ``metrics.jsonl`` (or a
shared ``span_dir`` is passed via ``--spans``), the cross-host
timeline section is stitched live through ``scripts/trace_timeline.py``
— per-host busy/wait totals, per-round barrier skew with the slowest
host named, and the flight-recorder postmortem (what each host was
doing when it died); ``--host`` restricts it to one host. The only
heavy import (jax, via utils.tracing) is deferred behind ``--trace``,
so metrics-only reporting is instant.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import trace_timeline  # noqa: E402  (scripts/trace_timeline.py, jax-free)

_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float]) -> str:
    """Unicode sparkline; constant series render flat, not empty."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = (hi - lo) or 1.0
    return "".join(
        _SPARK[int((v - lo) / span * (len(_SPARK) - 1))] for v in values
    )


def load_metrics(path: str) -> list[dict]:
    """Read metrics.jsonl records from a file or an artifacts dir."""
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no metrics.jsonl at {path!r} — pass a run's artifacts dir "
            "(log/<algo>/<dataset>/<model>/<run-id>_artifacts) or the "
            "file itself"
        )
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def summarize_client_health(records: list[dict]) -> dict | None:
    """Aggregate schema-v3 ``client_stats`` sub-objects into the
    client-health summary: per-round flag table, the update-norm
    divergence timeline, and per-client loss series when the records
    carry raw per-client values. None when no record has client stats."""
    cstats = [
        (r.get("round"), r["client_stats"]) for r in records
        if isinstance(r.get("client_stats"), dict)
    ]
    if not cstats:
        return None
    flagged_rounds = [
        {
            "round": rnd,
            "flagged": cs.get("flagged_clients", []),
            "reasons": cs.get("flag_reason", {}),
        }
        for rnd, cs in cstats if cs.get("flagged_clients")
    ]
    timeline = []
    for rnd, cs in cstats:
        un = (cs.get("quantiles") or {}).get("update_norm") or {}
        timeline.append({
            "round": rnd,
            "update_norm_p50": un.get("p50"),
            "update_norm_p100": un.get("p100"),
            "flagged": len(cs.get("flagged_clients") or []),
        })
    per_client_loss: dict[str, list] = {}
    for _, cs in cstats:
        pc = cs.get("per_client")
        if not pc:
            continue
        losses = pc.get("loss_after") or []
        for cid, loss in zip(pc.get("client_ids", []), losses):
            per_client_loss.setdefault(str(cid), []).append(loss)
    health: dict = {
        "rounds_reported": len(cstats),
        "total_flags": sum(len(f["flagged"]) for f in flagged_rounds),
        "flagged_rounds": flagged_rounds,
        "divergence_timeline": timeline,
    }
    if per_client_loss:
        health["per_client_loss"] = per_client_loss
    for key in ("quant_mse", "vote_agreement"):
        vals = [cs[key] for _, cs in cstats
                if isinstance(cs.get(key), (int, float))]
        if vals:
            health[key] = {
                "mean": round(statistics.mean(vals), 6),
                "last": round(vals[-1], 6),
            }
    return health


def summarize_valuation(records: list[dict],
                        flagged_ids: set[int] | None = None) -> dict | None:
    """Aggregate schema-v7 ``valuation`` sub-objects: the latest
    top-k/bottom-k client tables, the audit-correlation trail, and —
    when the records carry raw per-client values — the overlay against
    the client-health detector's flagged clients (an anomalous client
    should show a depressed valuation; agreement between the two
    independent signals is the check). None when no record carries
    valuation data."""
    vals = [
        (r.get("round"), r["valuation"]) for r in records
        if isinstance(r.get("valuation"), dict)
    ]
    if not vals:
        return None
    last_round, last = vals[-1]
    audits = [
        {"round": rnd, **v["audit"]}
        for rnd, v in vals if isinstance(v.get("audit"), dict)
    ]
    summary: dict = {
        "rounds_reported": len(vals),
        "n_clients": last.get("n_clients"),
        "last_round": last_round,
        "top_clients": last.get("top_clients", []),
        "bottom_clients": last.get("bottom_clients", []),
        "loss_delta_curve": [
            v.get("loss_delta") for _, v in vals
        ],
        "audits": audits,
        "last_audit": audits[-1] if audits else None,
    }
    pc = last.get("per_client")
    if flagged_ids and pc:
        # Flagged-vs-valuation overlay: each detector-flagged client's
        # current value and its rank (0 = most valuable). Rank is over
        # descending value with stable ties.
        ids = pc.get("client_ids", [])
        values = pc.get("value", [])
        by_id = dict(zip(ids, values))
        order = sorted(
            range(len(ids)), key=lambda i: -(values[i] or 0.0)
        )
        rank_of = {ids[i]: r for r, i in enumerate(order)}
        summary["flagged_overlay"] = [
            {
                "id": cid,
                "value": by_id.get(cid),
                "rank": rank_of.get(cid),
            }
            for cid in sorted(flagged_ids)
            if cid in by_id
        ]
    return summary


def summarize_async(records: list[dict]) -> dict | None:
    """Aggregate schema-v4 ``async`` sub-objects into the staleness
    summary: the buffer-occupancy timeline, a histogram over the
    recorded per-round mean staleness, and the simulated-clock speedup
    vs the synchronous wait-for-everyone counterfactual. None when no
    record carries async data."""
    asy = [
        (r.get("round"), r["async"]) for r in records
        if isinstance(r.get("async"), dict)
    ]
    if not asy:
        return None
    occupancy = [
        {"round": rnd, "buffer": a.get("buffer"),
         "applied": bool(a.get("applied"))}
        for rnd, a in asy
    ]
    sim_async = sum(
        a["sim_round_s"] for _, a in asy
        if isinstance(a.get("sim_round_s"), (int, float))
    )
    sim_sync = sum(
        a["sim_round_sync_s"] for _, a in asy
        if isinstance(a.get("sim_round_sync_s"), (int, float))
    )
    staleness = [
        a["mean_staleness"] for _, a in asy
        if isinstance(a.get("mean_staleness"), (int, float))
    ]
    # Integer-bucket histogram over the per-round mean staleness (the
    # records carry round means, not per-upload values — the honest
    # granularity to histogram).
    histogram: dict[str, int] = {}
    for s in staleness:
        histogram[str(int(s))] = histogram.get(str(int(s)), 0) + 1
    clocks = [
        a["sim_clock_s"] for _, a in asy
        if isinstance(a.get("sim_clock_s"), (int, float))
    ]
    return {
        "rounds_reported": len(asy),
        "late_total": sum(a.get("late") or 0 for _, a in asy),
        "on_time_total": sum(a.get("on_time") or 0 for _, a in asy),
        "applied_rounds": sum(1 for o in occupancy if o["applied"]),
        "occupancy_timeline": occupancy,
        "staleness_histogram": dict(
            sorted(histogram.items(), key=lambda kv: int(kv[0]))
        ),
        # Cumulative simulated clock (a resumed run's records carry the
        # carried-over clock, so this can exceed the per-file sums).
        "sim_clock_s": clocks[-1] if clocks else None,
        # THESE FILE'S rounds only — the async/sync pair the speedup
        # ratio is computed from, so the rendered numbers always
        # reproduce the rendered ratio.
        "sim_clock_async_s": round(sim_async, 6),
        "sim_clock_sync_s": round(sim_sync, 6),
        "speedup_vs_sync": (
            round(sim_sync / sim_async, 4) if sim_async > 0 else None
        ),
    }


def summarize_sweep(records: list[dict]) -> dict | None:
    """Aggregate schema-v8 ``sweep`` sub-objects into the sweep summary:
    the per-point accuracy table, the winner, and the compile-reuse
    bookkeeping (which points rode a warm program — the amortization the
    sweep engine exists for). None when no record belongs to a sweep."""
    by_point: dict[int, dict] = {}
    for r in records:
        sw = r.get("sweep")
        if not isinstance(sw, dict):
            continue
        entry = by_point.setdefault(sw["point"], {
            "point": sw["point"],
            "seed": sw.get("seed"),
            "lr": sw.get("lr"),
            "strategy": sw.get("strategy"),
            "group": sw.get("group"),
            "compile_reused": bool(sw.get("compile_reused")),
            "rounds": 0,
            "accuracies": [],
        })
        entry["rounds"] += 1
        if r.get("test_accuracy") is not None:
            entry["accuracies"].append(r["test_accuracy"])
    if not by_point:
        return None
    points = []
    for idx in sorted(by_point):
        e = by_point[idx]
        accs = e.pop("accuracies")
        e["final_accuracy"] = accs[-1] if accs else None
        e["best_accuracy"] = max(accs) if accs else None
        points.append(e)
    scored = [p for p in points if p["final_accuracy"] is not None]
    winner = (
        max(scored, key=lambda p: p["final_accuracy"]) if scored else None
    )
    reused = sum(1 for p in points if p["compile_reused"])
    return {
        "n_points": len(points),
        "strategies": sorted({p["strategy"] for p in points
                              if p["strategy"]}),
        "groups": len({p["group"] for p in points}),
        "rounds_total": sum(p["rounds"] for p in points),
        "compile_reuse_fraction": round(reused / len(points), 4),
        "points": points,
        "winner": (
            {"point": winner["point"], "seed": winner["seed"],
             "lr": winner["lr"],
             "final_accuracy": winner["final_accuracy"]}
            if winner else None
        ),
    }


def summarize_population(records: list[dict]) -> dict | None:
    """Aggregate schema-v9 ``population`` sub-objects into the
    open-world summary: the N-over-time curves, per-round join/depart
    counts, the planted drift cohort, and churn-rejected rounds
    (robustness/population.py). None when no record carries population
    data."""
    pops = [
        (r.get("round"), r["population"]) for r in records
        if isinstance(r.get("population"), dict)
    ]
    if not pops:
        return None
    timeline = [
        {"round": rnd, "n_alive": p.get("n_alive"),
         "n_registered": p.get("n_registered"),
         "joins": p.get("joins", 0), "departs": p.get("departs", 0)}
        for rnd, p in pops
    ]
    first_p = pops[0][1]
    last_p = pops[-1][1]
    # Every record carries the run's startup population; the derivation
    # fallback (first record's post-event count minus its joins) only
    # serves files written before n_initial landed, and is wrong for
    # partial files that don't start at round 0.
    n_initial = first_p.get(
        "n_initial",
        first_p.get("n_registered", 0) - first_p.get("joins", 0),
    )
    drift_ids = sorted({
        int(c) for _, p in pops for c in p.get("drift_clients", [])
    })
    return {
        "rounds_reported": len(pops),
        "n_initial": n_initial,
        "n_registered_final": last_p.get("n_registered"),
        "n_alive_final": last_p.get("n_alive"),
        "joins_total": sum(t["joins"] for t in timeline),
        "departs_total": sum(t["departs"] for t in timeline),
        "growth_ratio": (
            round(last_p["n_registered"] / n_initial, 4)
            if n_initial else None
        ),
        "timeline": timeline,
        "drift_cohort_size": last_p.get("drift_cohort_size", 0),
        "drift_clients": drift_ids,
        "churn_rejected_rounds": [
            rnd for rnd, p in pops if p.get("rejected_by_churn")
        ],
    }


def summarize_spans(records: list[dict]) -> dict | None:
    """Aggregate schema-v12 ``spans`` sub-objects (span_trace='on',
    telemetry/spans.py): run-total span counts and seconds by category,
    the DCN wait-vs-transfer split, and the per-round barrier-skew
    timeline (worst spill/checkpoint skew each round saw). None when no
    record carries span data."""
    sp = [
        (r.get("round"), r["spans"]) for r in records
        if isinstance(r.get("spans"), dict)
    ]
    if not sp:
        return None
    last = sp[-1][1]
    by_cat: dict[str, float] = {}
    for _, s in sp:
        for cat, secs in (s.get("seconds_by_cat") or {}).items():
            by_cat[cat] = by_cat.get(cat, 0.0) + secs
    skew_timeline = [
        {"round": rnd, "spill_skew_ms": s.get("spill_skew_ms"),
         "ckpt_skew_ms": s.get("ckpt_skew_ms")}
        for rnd, s in sp
    ]
    spills = [t["spill_skew_ms"] for t in skew_timeline
              if t["spill_skew_ms"] is not None]
    ckpts = [t["ckpt_skew_ms"] for t in skew_timeline
             if t["ckpt_skew_ms"] is not None]
    return {
        "rounds_reported": len(sp),
        "host_id": last.get("host_id"),
        "hosts": last.get("hosts"),
        "count": sum(int(s.get("count", 0)) for _, s in sp),
        "dropped": sum(int(s.get("dropped", 0)) for _, s in sp),
        "seconds_by_cat": {k: round(v, 6)
                           for k, v in sorted(by_cat.items())},
        "dcn_wait_s": round(
            sum(s.get("dcn_wait_s", 0.0) for _, s in sp), 6),
        "dcn_transfer_s": round(
            sum(s.get("dcn_transfer_s", 0.0) for _, s in sp), 6),
        "spill_skew_ms_max": max(spills) if spills else None,
        "ckpt_skew_ms_max": max(ckpts) if ckpts else None,
        "skew_timeline": skew_timeline,
    }


def summarize_run(records: list[dict], trace_stats: dict | None = None,
                  top_ops: list[dict] | None = None,
                  top_ops_time: list[dict] | None = None,
                  costmodel: dict | None = None,
                  span_timeline: dict | None = None) -> dict:
    """Aggregate metrics records into the machine-readable summary the
    terminal renderer and ``--json`` output share."""
    if not records:
        raise ValueError("metrics.jsonl holds no records")
    accs = [r.get("test_accuracy") for r in records]
    secs = [r["round_seconds"] for r in records if "round_seconds" in r]
    best_idx = max(
        range(len(records)),
        key=lambda i: -1.0 if accs[i] is None else accs[i],
    )
    summary: dict = {
        "rounds": len(records),
        "first_round": records[0].get("round"),
        "last_round": records[-1].get("round"),
        "schema_versions": sorted(
            {r.get("schema_version", 1) for r in records}
        ),
        "final_accuracy": accs[-1],
        "best_accuracy": accs[best_idx],
        "best_round": records[best_idx].get("round"),
        "accuracy_curve": accs,
        "round_seconds": {
            "total": sum(secs),
            "mean": statistics.mean(secs) if secs else None,
            "median": statistics.median(secs) if secs else None,
            "max": max(secs) if secs else None,
        },
    }
    rejected = [
        r.get("round") for r in records if r.get("round_rejected")
    ]
    summary["rejected_rounds"] = {"count": len(rejected), "rounds": rejected}

    # --- telemetry sub-objects (schema v2) ----------------------------------
    tels = [(r.get("round"), r["telemetry"]) for r in records
            if isinstance(r.get("telemetry"), dict)]
    if tels:
        # Batched dispatches (rounds_per_dispatch > 1) write ONE
        # telemetry sub-object per dispatch, on the dispatch's last
        # record, with ``dispatch_rounds`` saying how many rounds its
        # phase times cover — so summing over telemetry-carrying records
        # never double-counts, and the per-unit mean is labeled honestly
        # (per dispatch, not per round).
        batched_tel = any(
            tel.get("dispatch_rounds", 1) > 1 for _, tel in tels
        )
        phase_tot: dict[str, float] = {}
        per_round_phases = []
        for rnd, tel in tels:
            phases = tel.get("phase_seconds") or {}
            entry = {"round": rnd, **phases}
            if tel.get("dispatch_rounds", 1) > 1:
                entry["dispatch_rounds"] = tel["dispatch_rounds"]
            per_round_phases.append(entry)
            for name, secs_ in phases.items():
                phase_tot[name] = phase_tot.get(name, 0.0) + secs_
        grand = sum(phase_tot.values()) or 1.0
        summary["phases"] = {
            name: {
                "total_s": round(total, 3),
                "mean_s": round(total / len(tels), 4),
                "share": round(total / grand, 3),
            }
            for name, total in sorted(
                phase_tot.items(), key=lambda kv: -kv[1]
            )
        }
        summary["phase_unit"] = "dispatch" if batched_tel else "round"
        summary["phase_seconds_per_round"] = per_round_phases

        # Only when the records actually carry per-round compile counts
        # (the threaded oracle's records don't — its compile count is
        # run-scoped in the result dict): a missing key must not render
        # as a fabricated "0 compiles, shape-stable" verdict.
        if any("compiles" in tel for _, tel in tels):
            # Warmup = the first telemetry-carrying record (a batched
            # run's first dispatch records at its LAST round, not round
            # 0) or any record the simulator stamped ``warmup: true``
            # (the first dispatch of a new length legitimately compiles
            # its own scan program).
            warmup_round = tels[0][0]
            compile_rounds = [
                {"round": rnd, "compiles": tel.get("compiles", 0),
                 "compiled": tel.get("compiled", []),
                 "warmup": bool(
                     tel.get("warmup") or rnd == warmup_round
                 )}
                for rnd, tel in tels if tel.get("compiles")
            ]
            summary["compiles"] = {
                "total": sum(c["compiles"] for c in compile_rounds),
                "warmup": sum(c["compiles"] for c in compile_rounds
                              if c["warmup"]),
                "post_warmup": sum(c["compiles"] for c in compile_rounds
                                   if not c["warmup"]),
                "rounds": compile_rounds,
            }
        peaks = [tel["peak_hbm_bytes"] for _, tel in tels
                 if tel.get("peak_hbm_bytes")]
        summary["peak_hbm_bytes"] = max(peaks) if peaks else None

    # --- stream sub-objects (schema v5, client_residency='streamed') --------
    streams = [r["stream"] for r in records
               if isinstance(r.get("stream"), dict)]
    if streams:
        h2d_s = sum(s.get("h2d_seconds", 0.0) for s in streams)
        hidden_s = sum(s.get("hidden_seconds", 0.0) for s in streams)
        summary["stream"] = {
            "uploads": len(streams),
            "h2d_bytes": sum(s.get("h2d_bytes", 0) for s in streams),
            "h2d_seconds": round(h2d_s, 4),
            "hidden_seconds": round(hidden_s, 4),
            "overlap_ratio": round(hidden_s / h2d_s, 4) if h2d_s else 0.0,
            "d2h_bytes": sum(s.get("d2h_bytes", 0) for s in streams),
            "d2h_seconds": round(
                sum(s.get("d2h_seconds", 0.0) for s in streams), 4
            ),
        }
        # Cohort-draw replay accounting (participation_sampler,
        # ops/sampling.py): the sampler name + run-total sample time —
        # the host cost the `sample` phase row carries per round.
        samplers = {s["sampler"] for s in streams if s.get("sampler")}
        if samplers:
            summary["stream"]["sampler"] = "/".join(sorted(samplers))
            summary["stream"]["sample_ms"] = round(
                sum(s.get("sample_ms", 0.0) for s in streams), 3
            )

    # --- multihost sub-objects (schema v11, distributed shard store) --------
    mh_summary = summarize_multihost(records)
    if mh_summary is not None:
        summary["multihost"] = mh_summary

    # --- spans sub-objects (schema v12, span_trace='on') --------------------
    spans_summary = summarize_spans(records)
    if spans_summary is not None:
        summary["spans"] = spans_summary
    if span_timeline is not None:
        summary["span_timeline"] = span_timeline

    health = summarize_client_health(records)
    if health is not None:
        summary["client_health"] = health

    # --- valuation sub-objects (schema v7, client_valuation='on') -----------
    flagged_ids: set[int] = set()
    if health is not None:
        for fr in health["flagged_rounds"]:
            flagged_ids.update(int(c) for c in fr["flagged"])
    valuation = summarize_valuation(records, flagged_ids or None)
    if valuation is not None:
        summary["valuation"] = valuation

    async_summary = summarize_async(records)
    if async_summary is not None:
        summary["async_federation"] = async_summary

    # --- sweep sub-objects (schema v8, sweep/engine.py) ---------------------
    sweep_summary = summarize_sweep(records)
    if sweep_summary is not None:
        summary["sweep"] = sweep_summary

    # --- population sub-objects (schema v9, population='dynamic') -----------
    pop_summary = summarize_population(records)
    if pop_summary is not None:
        summary["population"] = pop_summary
        if valuation is not None and pop_summary["drift_clients"]:
            # Drift-cohort overlay on the PR 9 valuation tables: the
            # planted drifting clients SHOULD sink into the bottom-k
            # ranking; one surfacing in the top-k is the surprising
            # disagreement worth a look (the flagged-overlay pattern).
            drift = set(pop_summary["drift_clients"])
            valuation["drift_overlay"] = {
                "drift_in_bottom": [
                    e["id"] for e in valuation["bottom_clients"]
                    if e["id"] in drift
                ],
                "drift_in_top": [
                    e["id"] for e in valuation["top_clients"]
                    if e["id"] in drift
                ],
            }

    # --- costmodel sub-object (schema v6, cost_model_trace) -----------------
    # Explicit costmodel (computed live from --trace) wins; otherwise the
    # LAST record carrying one (the simulator attaches it to the run's
    # final record).
    if costmodel is None:
        cms = [r["costmodel"] for r in records
               if isinstance(r.get("costmodel"), dict)]
        costmodel = cms[-1] if cms else None
    if costmodel is not None:
        summary["costmodel"] = costmodel

    if trace_stats is not None:
        summary["trace"] = trace_stats
    if top_ops is not None:
        summary["top_device_ops"] = top_ops
    if top_ops_time is not None:
        summary["top_device_ops_time"] = top_ops_time
    return summary


def summarize_multihost(records: list[dict]) -> dict | None:
    """schema-v11 ``multihost`` sub-objects: the distributed shard
    store's per-host assembly provenance (parallel/streaming
    .DistributedCohortStreamer). The shard-ownership fields are static
    per run (last record wins); spill/DCN traffic accumulates over the
    recorded rounds. None for single-process runs — the off-gate
    rendering convention."""
    mhs = [r["multihost"] for r in records
           if isinstance(r.get("multihost"), dict)]
    if not mhs:
        return None
    last = mhs[-1]
    overlaps = [m["overlap_ratio"] for m in mhs
                if m.get("overlap_ratio") is not None]
    return {
        "hosts": last["hosts"],
        "host_id": last["host_id"],
        "owned_clients": last["owned_clients"],
        "shard_bytes": last["shard_bytes"],
        "rounds_reported": len(mhs),
        "spill_rows": sum(int(m.get("spill_rows", 0)) for m in mhs),
        "dcn_bytes": sum(int(m.get("dcn_bytes", 0)) for m in mhs),
        "mean_overlap_ratio": (
            round(sum(overlaps) / len(overlaps), 4) if overlaps else 0.0
        ),
    }


def render_summary(summary: dict) -> list[str]:
    """Terminal rendering of :func:`summarize_run`'s output."""
    lines = []
    v = "/".join(str(s) for s in summary["schema_versions"])
    lines.append(
        f"run: rounds {summary['first_round']}..{summary['last_round']} "
        f"({summary['rounds']} recorded, metrics schema v{v})"
    )
    if "multihost" in summary:
        # The manifest line of the run header: which host's record
        # stream this artifact dir holds, and its shard of the
        # host-sharded population (per-host checkpoint shards carry the
        # same split — utils/checkpoint.py manifests).
        m = summary["multihost"]
        lines.append(
            f"manifest: {m['hosts']}-host distributed shard store — "
            f"this record stream is host {m['host_id']}, owning "
            f"{m['owned_clients']} clients "
            f"({m['shard_bytes'] / 2**20:.1f} MiB shard)"
        )
    accs = [a for a in summary["accuracy_curve"] if a is not None]
    if accs:
        lines.append(
            f"accuracy: final {summary['final_accuracy']:.4f}, "
            f"best {summary['best_accuracy']:.4f} "
            f"@ round {summary['best_round']}"
        )
        lines.append(f"  curve: {sparkline(accs)}")
    rs = summary["round_seconds"]
    if rs["mean"] is not None:
        lines.append(
            f"round time: total {rs['total']:.2f}s, mean {rs['mean']:.3f}s, "
            f"median {rs['median']:.3f}s, max {rs['max']:.3f}s"
        )
    rej = summary["rejected_rounds"]
    if rej["count"]:
        lines.append(
            f"rejected rounds (quorum): {rej['count']} — {rej['rounds']}"
        )
    else:
        lines.append("rejected rounds (quorum): 0")

    if "phases" in summary:
        unit = summary.get("phase_unit", "round")
        lines.append(
            f"phase breakdown (per-{unit} mean, share of phased time):"
        )
        for name, st in summary["phases"].items():
            bar = "#" * max(1, int(st["share"] * 40))
            lines.append(
                f"  {name:<12} {st['mean_s']:>9.4f}s  "
                f"{st['share']:>6.1%}  {bar}"
            )
    if "stream" in summary:
        # The host->HBM transfer row (client_residency='streamed'): kept
        # visually with the phase table, but NOT a share of phased time —
        # the prefetch's point is that this time overlaps client_step.
        s = summary["stream"]
        per_upload = s["h2d_seconds"] / max(s["uploads"], 1)
        bar = "#" * max(1, int(s["overlap_ratio"] * 40))
        lines.append(
            f"  {'h2d_stream':<12} {per_upload:>9.4f}s  "
            f"{s['overlap_ratio']:>6.1%} hidden  {bar}"
        )
        lines.append(
            f"  streamed transfers: {s['uploads']} upload(s), "
            f"{s['h2d_bytes'] / 2**20:.1f} MiB h2d"
            + (
                f", {s['d2h_bytes'] / 2**20:.1f} MiB d2h "
                f"({s['d2h_seconds']:.3f}s state writeback)"
                if s["d2h_bytes"] else ""
            )
        )
        if s.get("sampler"):
            lines.append(
                f"  cohort sampler: {s['sampler']} "
                f"({s['sample_ms']:.1f} ms total replay — the `sample` "
                "phase row)"
            )
    if "multihost" in summary:
        # Per-host shard summary (schema v11): this host's share of the
        # owner-sharded assembly — spill is the per-round ownership
        # imbalance, the ONLY client data that crosses DCN.
        m = summary["multihost"]
        lines.append(
            f"  distributed store: host {m['host_id']}/{m['hosts']} "
            f"served {m['rounds_reported']} round(s); spill "
            f"{m['spill_rows']} row(s), "
            f"{m['dcn_bytes'] / 2**20:.2f} MiB over DCN, mean upload "
            f"overlap {m['mean_overlap_ratio']:.1%}"
        )
    if "spans" in summary:
        # Distributed-trace rollup (schema v12): the in-record view —
        # what the spans sub-objects alone say, no journals needed.
        sp = summary["spans"]
        dropped = f", {sp['dropped']} dropped" if sp["dropped"] else ""
        lines.append(
            f"span trace: host {sp['host_id']}/{sp['hosts']}, "
            f"{sp['count']} span(s) over {sp['rounds_reported']} "
            f"round(s){dropped}; DCN wait {sp['dcn_wait_s']:.3f}s vs "
            f"transfer {sp['dcn_transfer_s']:.3f}s"
        )
        skews = []
        if sp["spill_skew_ms_max"] is not None:
            skews.append(f"spill {sp['spill_skew_ms_max']:.3f} ms")
        if sp["ckpt_skew_ms_max"] is not None:
            skews.append(f"checkpoint {sp['ckpt_skew_ms_max']:.3f} ms")
        if skews:
            lines.append(
                f"  worst barrier skew: {', '.join(skews)}"
            )
        spill_curve = [t["spill_skew_ms"] for t in sp["skew_timeline"]
                       if t["spill_skew_ms"] is not None]
        if len(spill_curve) > 1:
            lines.append(
                f"  spill skew/round: {sparkline(spill_curve)}  "
                f"[{min(spill_curve):.2f} .. {max(spill_curve):.2f} ms]"
            )
    if "span_timeline" in summary:
        # Cross-host view stitched from the spans_*.jsonl journals
        # (scripts/trace_timeline.py): barrier skew with the slowest
        # host named, per-host busy/wait split, and the flight-recorder
        # postmortem — the section that answers "which HOST stalled".
        lines.append("distributed trace (stitched span journals):")
        for tl in trace_timeline.render_text(
            summary["span_timeline"]
        ).splitlines():
            lines.append(f"  {tl}")
    if "compiles" in summary:
        c = summary["compiles"]
        lines.append(
            f"XLA compiles: {c['total']} total "
            f"({c['warmup']} warmup, {c['post_warmup']} post-warmup)"
        )
        for cr in c["rounds"]:
            if not cr.get("warmup"):
                names = ", ".join(cr["compiled"]) or "<unknown>"
                lines.append(
                    f"  !! round {cr['round']}: {cr['compiles']} "
                    f"recompile(s) after warmup — {names}"
                )
        if c["post_warmup"] == 0:
            lines.append("  post-warmup recompiles: none (shape-stable run)")
    peak = summary.get("peak_hbm_bytes")
    if peak:
        lines.append(f"peak HBM: {peak / 2**30:.2f} GiB")
    elif "phases" in summary:
        lines.append("peak HBM: unavailable on this backend")

    if "client_health" in summary:
        h = summary["client_health"]
        lines.append(
            f"client health: {h['rounds_reported']} round(s) with stats, "
            f"{h['total_flags']} anomaly flag(s)"
        )
        for fr in h["flagged_rounds"]:
            reasons = ", ".join(
                f"{cid}:{reason}" for cid, reason in fr["reasons"].items()
            )
            lines.append(
                f"  !! round {fr['round']}: flagged {fr['flagged']} "
                f"({reasons})"
            )
        p100 = [
            t["update_norm_p100"] for t in h["divergence_timeline"]
            if t["update_norm_p100"] is not None
        ]
        if p100:
            lines.append(
                f"  divergence timeline (max update norm/round): "
                f"{sparkline(p100)}  "
                f"[{min(p100):.4g} .. {max(p100):.4g}]"
            )
        for key, label in (("quant_mse", "downlink quantization MSE"),
                           ("vote_agreement", "vote agreement")):
            if key in h:
                lines.append(
                    f"  {label}: mean {h[key]['mean']:.6g}, "
                    f"last {h[key]['last']:.6g}"
                )
        loss_series = h.get("per_client_loss") or {}
        if loss_series:
            lines.append("  per-client local loss (round series):")
            for cid in sorted(loss_series, key=int)[:16]:
                series = [v for v in loss_series[cid] if v is not None]
                last = f"{series[-1]:.4f}" if series else "n/a"
                lines.append(
                    f"    client {cid:>4}: {sparkline(series):<12} "
                    f"last {last}"
                )
            if len(loss_series) > 16:
                lines.append(
                    f"    ... {len(loss_series) - 16} more client(s)"
                )

    if "valuation" in summary:
        v = summary["valuation"]
        lines.append(
            f"client valuation: {v['rounds_reported']} round(s) of "
            f"streaming scores over {v['n_clients']} client(s)"
        )
        deltas = [d for d in v["loss_delta_curve"] if d is not None]
        if deltas:
            lines.append(
                f"  loss-delta curve: {sparkline(deltas)}  "
                f"[{min(deltas):+.4g} .. {max(deltas):+.4g}]"
            )

        def _ranked(label, entries):
            if not entries:
                return
            row = ", ".join(
                f"{e['id']}:{e['value']:+.3g}" for e in entries
            )
            lines.append(f"  {label}: {row}")

        _ranked("top clients   ", v["top_clients"])
        _ranked("bottom clients", v["bottom_clients"])
        for o in v.get("flagged_overlay", []):
            # The incentive-side read of the anomaly detector: a flagged
            # client sitting at a HIGH valuation rank is the surprising
            # case worth a look — the two independent signals disagree.
            val = "n/a" if o["value"] is None else f"{o['value']:+.3g}"
            lines.append(
                f"  !! flagged client {o['id']}: valuation {val} "
                f"(rank {o['rank']}/{v['n_clients']}, 0 = most valuable)"
            )
        ov = v.get("drift_overlay")
        if ov:
            # Planted drifting-quality cohort (population='dynamic')
            # against the valuation ranking: sinking into the bottom-k
            # is the expected direction; a drifting client in the top-k
            # is the disagreement worth a look.
            lines.append(
                f"  drift overlay: {len(ov['drift_in_bottom'])}/"
                f"{len(v['bottom_clients'])} of bottom clients are "
                f"planted drifters"
                + (
                    f"; !! drifters in TOP clients: "
                    f"{ov['drift_in_top']}"
                    if ov["drift_in_top"] else ""
                )
            )
        if v["last_audit"] is not None:
            a = v["last_audit"]
            hit = (
                f", memo hit {a['memo_hit_rate']:.0%}"
                if a.get("memo_hit_rate") is not None else ""
            )
            sp = a.get("spearman")
            pe = a.get("pearson")
            # Audit cost face (mesh-sharded GTG): wall seconds + how many
            # devices the walk's subset evaluation partitioned over
            # (absent on pre-v10-era records — rendered only when known).
            secs = a.get("seconds")
            devs = a.get("devices")
            cost = ""
            if secs is not None:
                cost = f", {secs:.1f}s" + (
                    f" on {devs} device(s)" if devs is not None else ""
                )
            lines.append(
                "  GTG audit (round {}): spearman {} pearson {} over {} "
                "permutation(s), converged={}{}{}".format(
                    a["round"],
                    "n/a" if sp is None else f"{sp:.3f}",
                    "n/a" if pe is None else f"{pe:.3f}",
                    a["permutations"], a["converged"], hit, cost,
                )
            )

    if "population" in summary:
        p = summary["population"]
        lines.append(
            f"dynamic population: {p['n_initial']} -> "
            f"{p['n_registered_final']} registered clients "
            f"({p['joins_total']} joined, {p['departs_total']} departed, "
            f"{p['n_alive_final']} alive"
            + (
                f", growth {p['growth_ratio']:.2f}x"
                if p["growth_ratio"] is not None else ""
            )
            + ")"
        )
        alive_curve = [
            t["n_alive"] for t in p["timeline"]
            if t["n_alive"] is not None
        ]
        if alive_curve:
            lines.append(
                f"  alive N over time: {sparkline(alive_curve)}  "
                f"[{min(alive_curve)} .. {max(alive_curve)}]"
            )
        joins = [t["joins"] for t in p["timeline"]]
        departs = [t["departs"] for t in p["timeline"]]
        if any(joins):
            lines.append(
                f"  joins/round:   {sparkline(joins)}  "
                f"(total {sum(joins)})"
            )
        if any(departs):
            lines.append(
                f"  departs/round: {sparkline(departs)}  "
                f"(total {sum(departs)})"
            )
        if p["drift_cohort_size"]:
            ids = p["drift_clients"]
            lines.append(
                f"  planted drift cohort: {p['drift_cohort_size']} "
                "client(s)"
                + (f" {ids}" if ids else "")
            )
        if p["churn_rejected_rounds"]:
            lines.append(
                "  !! rounds rejected by churn (departures pushed "
                f"survivors below quorum): {p['churn_rejected_rounds']}"
            )

    if "async_federation" in summary:
        a = summary["async_federation"]
        lines.append(
            f"async federation: {a['rounds_reported']} round(s), "
            f"{a['late_total']} late / {a['on_time_total']} on-time "
            f"upload(s), buffer applied in {a['applied_rounds']} round(s)"
        )
        occ = [
            o["buffer"] for o in a["occupancy_timeline"]
            if o["buffer"] is not None
        ]
        if occ:
            lines.append(
                f"  buffer occupancy/round: {sparkline(occ)}  "
                f"[{min(occ)} .. {max(occ)}]"
            )
        if a["staleness_histogram"]:
            total = sum(a["staleness_histogram"].values())
            lines.append("  staleness histogram (round means):")
            for bucket, count in a["staleness_histogram"].items():
                bar = "#" * max(1, int(count / total * 40))
                lines.append(f"    s={bucket:>3}: {count:>4}  {bar}")
        if a["speedup_vs_sync"] is not None:
            # Per-file sums on both sides: the printed pair reproduces
            # the printed ratio even on resumed runs, whose cumulative
            # sim_clock_s exceeds this file's rounds.
            lines.append(
                f"  simulated clock: {a['sim_clock_async_s']:.1f}s async "
                f"vs {a['sim_clock_sync_s']:.1f}s sync — "
                f"{a['speedup_vs_sync']:.2f}x speedup"
            )
    if "sweep" in summary:
        sw = summary["sweep"]
        strategies = "/".join(sw["strategies"]) or "?"
        lines.append(
            f"sweep: {sw['n_points']} point(s), strategy {strategies}, "
            f"{sw['groups']} config-hash group(s), "
            f"{sw['rounds_total']} experiment-rounds"
        )
        lines.append(
            f"  compile reuse: {sw['compile_reuse_fraction']:.0%} of "
            "points rode a warm program"
        )
        lines.append("  point  seed        lr  warm  final acc  best acc")
        for p in sw["points"]:
            fin = (
                f"{p['final_accuracy']:.4f}"
                if p["final_accuracy"] is not None else "n/a"
            )
            best = (
                f"{p['best_accuracy']:.4f}"
                if p["best_accuracy"] is not None else "n/a"
            )
            lr = f"{p['lr']:.4g}" if p["lr"] is not None else "?"
            warm = "yes" if p["compile_reused"] else "no"
            lines.append(
                f"  {p['point']:>5}  {p['seed']!s:>4}  {lr:>8}  "
                f"{warm:>4}  {fin:>9}  {best:>8}"
            )
        if sw["winner"] is not None:
            w = sw["winner"]
            lines.append(
                f"  winner: point {w['point']} (seed {w['seed']}, "
                f"lr {w['lr']:.4g}) at {w['final_accuracy']:.4f}"
            )
        cm = summary.get("costmodel")
        if cm is not None and cm.get("per_topology"):
            # $/sweep (telemetry/costmodel.py pricing discipline): the
            # compiled program priced once, multiplied by the sweep's
            # experiment-round occupancy — per topology-table entry.
            lines.append(
                f"  $/sweep ({sw['rounds_total']} experiment-rounds):"
            )
            for name, t in cm["per_topology"].items():
                usd = t.get("usd_per_round")
                if usd is None:
                    continue
                lines.append(
                    f"    {name:<10} ${usd * sw['rounds_total']:.4f}"
                    f"  (x{t['chips']} chips, "
                    f"{t['predicted_ms']:.1f} ms/round predicted)"
                )
    if "costmodel" in summary:
        # "What would this cost at scale": the roofline prediction per
        # topology-table entry, measured run as the anchor row.
        cm = summary["costmodel"]
        run_rounds = cm.get("run_rounds")
        horizon = f" @ {run_rounds} rounds" if run_rounds else ""
        lines.append(
            f"cost at scale (roofline on the traced ledger; "
            f"anchor {cm['anchor_topology']}{horizon}):"
        )
        if cm.get("measured_ms") is not None:
            lines.append(
                f"  measured   {cm['anchor_topology']:<10} "
                f"round {cm['measured_ms']:>10.1f} ms  (this run — "
                f"anchor)"
            )
        for name, t in (cm.get("per_topology") or {}).items():
            usd_run = t.get("usd_per_run")
            cost = (
                f"  ${usd_run:.2f}/run" if usd_run is not None else
                f"  ${t.get('usd_per_round', 0):.6f}/round"
            )
            lines.append(
                f"  predicted  {name:<10} "
                f"round {t['predicted_ms']:>10.1f} ms  x{t['chips']:<4}"
                f"{t.get('bottleneck', '?')}-bound{cost}"
            )
        if cm.get("model_error_ratio") is not None:
            lines.append(
                f"  model error: predicted/measured = "
                f"{cm['model_error_ratio']:.3f} "
                f"(band gated by compare_bench --model-drift-threshold)"
            )
        cats = cm.get("categories") or {}
        if cats:
            lines.append("  per-category roofline (per round, anchor):")
            for cat, c in sorted(
                cats.items(), key=lambda kv: -kv[1]["predicted_ms"]
            ):
                lines.append(
                    f"    {cat:<12} {c['predicted_ms']:>9.2f} ms "
                    f"predicted  {c['bytes_gb']:>8.2f} GB  "
                    f"{c.get('bottleneck', '?')}-bound"
                )
    if "trace" in summary:
        t = summary["trace"]
        lines.append(
            f"device trace: {t['device_ms']:.1f} ms device time, "
            f"{t['bytes_gb']:.3f} GB accessed, {t['op_count']} ops"
        )
    if summary.get("top_device_ops"):
        lines.append("top device ops by bytes:")
    for op in summary.get("top_device_ops", []):
        lines.append(
            f"  {op['bytes_gb']:>8.3f} GB  {op['device_ms']:>8.2f} ms  "
            f"x{op['count']:<5} {op['name']}"
        )
    if summary.get("top_device_ops_time"):
        lines.append("top device ops by time:")
    for op in summary.get("top_device_ops_time", []):
        lines.append(
            f"  {op['device_ms']:>8.2f} ms  {op['bytes_gb']:>8.3f} GB  "
            f"x{op['count']:<5} {op['name']}"
        )
    return lines


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Render a run's artifacts dir into a telemetry summary"
    )
    ap.add_argument("artifacts",
                    help="run artifacts dir or metrics.jsonl path")
    ap.add_argument("--trace", default=None,
                    help="jax.profiler trace dir (config.profile_dir)")
    ap.add_argument("--json", default=None,
                    help="also write the summary as JSON to this path")
    ap.add_argument("--top", type=int, default=10,
                    help="top-K device ops from --trace (default 10)")
    ap.add_argument("--trace-rounds", type=int, default=1,
                    help="rounds the --trace covers (per-round basis of "
                         "the cost model; default 1)")
    ap.add_argument("--cost-topology", default=None,
                    help="topology-table anchor for the --trace cost "
                         "model (default: costmodel.DEFAULT_ANCHOR)")
    ap.add_argument("--cost-rounds", type=int, default=None,
                    help="run horizon for the $/run projection (default: "
                         "this run's recorded round count)")
    ap.add_argument("--spans", default=None,
                    help="directory holding spans_*.jsonl host journals "
                         "(default: the artifacts dir itself)")
    ap.add_argument("--host", type=int, default=None,
                    help="restrict the distributed-trace section to one "
                         "host id")
    args = ap.parse_args(argv)

    try:
        records = load_metrics(args.artifacts)
        span_timeline = None
        span_dir = args.spans or (
            args.artifacts if os.path.isdir(args.artifacts)
            else os.path.dirname(args.artifacts)
        )
        journal_paths = trace_timeline.find_journals([span_dir]) \
            if os.path.isdir(span_dir) else []
        if journal_paths:
            span_timeline = trace_timeline.summarize(
                [trace_timeline.load_journal(p) for p in journal_paths],
                host=args.host,
            )
        trace_stats = top_ops = top_ops_time = costmodel = None
        if args.trace:
            # Deferred: utils.tracing imports jax. One gzip pass serves
            # the totals and both rankings; a second builds the cost
            # model's categorized ledger.
            from distributed_learning_simulator_tpu.telemetry.costmodel import (  # noqa: E501
                DEFAULT_ANCHOR,
                costmodel_record,
                ledger_totals,
            )
            from distributed_learning_simulator_tpu.utils.tracing import (
                categorize_ops,
                device_op_report,
            )

            report = device_op_report(args.trace, k=args.top)
            trace_stats = report["totals"]
            top_ops = report["by_bytes"]
            top_ops_time = report["by_time"]
            ledger = categorize_ops(args.trace)
            if ledger and ledger_totals(ledger)["bytes_gb"] > 0:
                # Anchor on this run's measured steady rounds (round 0
                # carries compile when more than one record exists).
                secs = [r["round_seconds"] for r in records
                        if "round_seconds" in r]
                steady = secs[1:] or secs
                costmodel = costmodel_record(
                    ledger,
                    trace_rounds=args.trace_rounds,
                    anchor=args.cost_topology or DEFAULT_ANCHOR,
                    measured_ms=(
                        1e3 * statistics.median(steady) if steady else None
                    ),
                    run_rounds=args.cost_rounds or len(records),
                )
        summary = summarize_run(records, trace_stats=trace_stats,
                                top_ops=top_ops, top_ops_time=top_ops_time,
                                costmodel=costmodel,
                                span_timeline=span_timeline)
    except (FileNotFoundError, ValueError) as e:
        print(str(e), file=sys.stderr)
        return 2
    for line in render_summary(summary):
        print(line)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=2)
        print(f"summary JSON: {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
