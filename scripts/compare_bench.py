"""Diff two bench JSON records with a relative-threshold regression gate.

The BENCH_r*.json trajectory used to be eyeball-only: a reviewer had to
hand-diff nested JSON to notice a lost fusion or a slower flagship leg.
This tool makes it a gate:

    python scripts/compare_bench.py OLD.json NEW.json [--threshold 0.05]
        [--force] [--json]

* Exit 0 — no tracked metric regressed beyond the threshold.
* Exit 1 — regression(s): any tracked metric moved in its BAD direction
  by more than ``--threshold`` (relative). Each is printed with both
  values and the relative change.
* Exit 2 — the runs are not comparable (``config_hash`` mismatch — the
  program-defining knobs differ — or bench ``schema_version`` mismatch)
  and ``--force`` was not given. Records predating the provenance stamp
  (no ``schema_version``/``config_hash``) compare value-by-value with a
  warning; the gate cannot prove comparability for them.

Tracked metrics (missing on either side -> skipped, listed as such):
headline/flagship rates (higher is better), converged-GTG round seconds
(lower), the deterministic traced-bytes proxies (lower — these are
byte-exact program properties, so ANY growth beyond the threshold is a
real program change), rejected-round and survivor robustness counters.

Two in-record gates run on the NEW record alone: its ``client_stats``
sub-object already holds the on-vs-off round-time overhead measured
within that single bench run (bench.py re-runs the headline program
with client_stats='on'), so an overhead above
``--stats-overhead-threshold`` is a regression regardless of the old
record — the feature's promise is "cheap enough to leave on". The
ratio is judged ABSOLUTELY, never as a tracked relative metric: it
hovers near zero, where relative changes are pure noise. The
``round_batch`` leg's ``amortization_ratio`` (rounds_per_dispatch
K-vs-1 rate ratio, measured within the run) gets the same treatment:
``--batch-amortization-threshold`` is an absolute floor — it hovers
near 1.0, where a relative gate would flap. So does the ``async``
leg's ``async_speedup_ratio`` (simulated-clock speedup of deadline
rounds over the sync counterfactual): ``--async-speedup-threshold``
is an absolute floor, default 1.0. And the ``stream`` leg's prefetch
``overlap_ratio`` (fraction of host->HBM upload time hidden behind
compute at the largest swept population, client_residency='streamed'):
``--stream-overlap-threshold`` is an absolute floor, default 0.5 —
and the same leg's ``cohort_rate`` (steady cohort·rounds/s at that
population under the fastest-supported ``participation_sampler``)
gets ``--stream-cohort-rate-threshold`` as an absolute floor, default
900: the O(cohort) hashed sampler retired the exact replay's ~1 s/round
host-bound ceiling (328 c·r/s at N=1e6, r07), and the gate keeps the
million-client leg model-bound. The
``valuation`` leg's ``audit_spearman`` (streaming client-valuation
vector vs cumulative exact-GTG audit SVs on the graded-quality
differential config, telemetry/valuation.py) gets
``--valuation-corr-threshold`` as an absolute floor, default 0.8 —
the cheap estimator must keep tracking exact Shapley. The ``sweep``
leg's ``sweep_amortization_ratio`` (serial-solo vs vmapped-fleet wall
for the same points, sweep/engine.py) gets
``--sweep-amortization-threshold`` as an absolute floor, default 2.0 —
the fleet must at least halve the sweep's wall-clock (compile paid
once is the whole multiplier). The ``churn`` leg's
``churn_overhead_ratio`` (10x population-growth dynamic run vs the
same program static, robustness/population.py) gets
``--churn-overhead-threshold`` as an absolute ceiling, default 0.10 —
the registration stream must ride the round at marginal cost, never
relatively tracked. The
``gtg`` leg's ``gtg_scaling_ratio`` (D=2/D=1 subset-eval throughput of
the mesh-sharded GTG walk's scaling microbench, algorithms/shapley.py)
gets ``--gtg-scaling-threshold`` as an absolute floor, default 1.5 —
two devices must buy at least half a device's worth of extra walk
throughput, never relatively tracked; bench arms the key only when the
host could honestly measure it (>= 2 usable cores — a 1-core cgroup
cannot overlap two devices' compute, and the unarmed measurement stays
in the record under ``gtg.scaling``). The ``mhost`` leg's
``mhost_cohort_rate`` (steady cohort-rounds/s of the 2-process
distributed-shard-store N-sweep at its largest population,
parallel/streaming.DistributedCohortStreamer) gets
``--mhost-cohort-rate-threshold`` as an absolute floor, default 200 —
the owner-sharded data plane (cohort assembly + spill exchange +
per-host placement) must keep the composed streamed x multihost run
off the host-bound floor, never relatively tracked; armed like the gtg
gate only on hosts with >= 2 usable cores (a 1-core cgroup cannot
overlap two processes' compute — the honest number stays unarmed
under ``mhost.cohort_rate``). The ``spans`` leg's ``overhead_ratio``
(headline re-run with ``span_trace='on'``, telemetry/spans.py) gets
``--span-overhead-threshold`` as an absolute ceiling, default 0.05 —
the distributed tracer's promise is "cheap enough to leave on", and
like the client-stats overhead the near-zero ratio is never relatively
tracked. The
``costmodel`` leg's ``model_error_ratio`` per program (predicted /
measured per-round ms from the roofline model, telemetry/costmodel.py)
is judged as an absolute BAND around 1.0 (``--model-drift-threshold``,
default 0.35 — wide enough for the documented ~25% device-vs-wall
host-side share on the cnn headline, docs/PERFORMANCE.md § Predicted
pod-scale cost): a prediction drifting out of band means the program
changed character faster than the fitted model — refit deliberately
(docs update) instead of letting capacity plans rot silently.

Deliberately imports nothing heavy (no jax): usable as a CI gate and
fast enough to self-test in tier-1 (tests/test_compare_bench.py).
"""

from __future__ import annotations

import argparse
import json
import sys

# (dotted path, direction, description). Direction is the GOOD direction;
# a relative move against it beyond the threshold is a regression.
TRACKED = [
    ("value", "higher", "headline median clients*rounds/s"),
    ("mean_rate", "higher", "headline mean clients*rounds/s"),
    ("flagship.value", "higher", "flagship median clients*rounds/s"),
    ("gtg.value", "lower", "converged-GTG round seconds"),
    ("proxy.traced_bytes_gb", "lower", "cnn traced bytes proxy (GB)"),
    ("proxy.traced_op_count", "lower", "cnn traced op count"),
    ("proxy_flagship.traced_bytes_gb", "lower",
     "flagship traced bytes proxy (GB)"),
    ("proxy_flagship.traced_op_count", "lower", "flagship traced op count"),
    ("robustness.rounds_rejected", "lower", "quorum-rejected rounds"),
    ("robustness.mean_survivor_count", "higher", "mean survivor count"),
    # client_stats.overhead_ratio is deliberately NOT tracked here: it is
    # the difference of two noisy medians hovering near zero, so a
    # relative-change gate on it would flap (0.01 -> 0.02 reads as
    # +100%). The absolute in-record gate (overhead_gate) is the designed
    # mechanism. costmodel.*.model_error_ratio follows the same rule
    # (near-1.0 ratios must never be tracked relatively — PR 4/5
    # precedent): the absolute band gate (model_drift_gate) judges it.
]


def get_path(record: dict, dotted: str):
    """Resolve a dotted path; None when any hop is missing/non-numeric."""
    node = record
    for part in dotted.split("."):
        if not isinstance(node, dict) or part not in node:
            return None
        node = node[part]
    return node if isinstance(node, (int, float)) and not isinstance(
        node, bool
    ) else None


def check_comparable(old: dict, new: dict) -> str | None:
    """Reason the two records must NOT be gate-compared, or None if OK.

    Refusal needs the stamp on BOTH sides: pre-provenance records (bench
    schema v1, no stamp) can't prove incomparability, so they pass with
    the caveat printed by main().
    """
    o_v, n_v = old.get("schema_version"), new.get("schema_version")
    if o_v is not None and n_v is not None and o_v != n_v:
        return f"bench schema_version differs: {o_v} vs {n_v}"
    o_h, n_h = old.get("config_hash"), new.get("config_hash")
    if o_h is not None and n_h is not None and o_h != n_h:
        return (
            f"config_hash differs: {o_h} vs {n_h} — the runs measured "
            "different programs (model/population/chunk/dtype/failure "
            "knobs); re-run one side or pass --force"
        )
    return None


def compare_records(old: dict, new: dict, threshold: float = 0.05) -> dict:
    """Pure comparison: returns ``{"regressions", "improvements",
    "unchanged", "skipped"}`` lists of per-metric dicts."""
    out = {"regressions": [], "improvements": [], "unchanged": [],
           "skipped": []}
    for dotted, direction, desc in TRACKED:
        o, n = get_path(old, dotted), get_path(new, dotted)
        if o is None or n is None:
            out["skipped"].append({"metric": dotted, "description": desc})
            continue
        if o == 0:
            # Relative change undefined; only an absolute move in the bad
            # direction counts (covers counters like rounds_rejected=0).
            bad = (n > 0) if direction == "lower" else (n < 0)
            rel = None
        else:
            rel = (n - o) / abs(o)
            worse = -rel if direction == "higher" else rel
            bad = worse > threshold
        entry = {
            "metric": dotted, "description": desc, "old": o, "new": n,
            "relative_change": rel, "direction": direction,
        }
        if bad:
            out["regressions"].append(entry)
        elif rel is not None and abs(rel) > threshold:
            out["improvements"].append(entry)
        else:
            out["unchanged"].append(entry)
    return out


def overhead_gate(record: dict, threshold: float) -> dict | None:
    """In-record client-stats overhead gate (see module docstring): the
    regression entry when the record's own measured ``client_stats=on``
    overhead exceeds ``threshold``, else None (absent leg included)."""
    ratio = get_path(record, "client_stats.overhead_ratio")
    if ratio is None or ratio <= threshold:
        return None
    return {
        "metric": "client_stats.overhead_ratio",
        "description": (
            "client_stats=on round-time overhead vs the same run's "
            "off-mode headline"
        ),
        "old": threshold, "new": ratio,
        "relative_change": None, "direction": "lower",
    }


def batch_amortization_gate(record: dict, threshold: float) -> dict | None:
    """In-record round-batching gate: bench.py's ``round_batch`` leg
    measures the K-vs-1 rate ratio of ``rounds_per_dispatch`` within one
    run, so a ratio below ``threshold`` means batching stopped paying for
    itself — a regression regardless of the old record. Judged
    ABSOLUTELY (like the client-stats overhead): the ratio hovers near
    1.0, where a relative-change gate would flap. None when the leg is
    absent or the ratio holds."""
    ratio = get_path(record, "round_batch.amortization_ratio")
    if ratio is None or ratio >= threshold:
        return None
    return {
        "metric": "round_batch.amortization_ratio",
        "description": (
            "rounds_per_dispatch=K vs K=1 rate ratio from the same "
            "bench run (>= 1.0 means batching pays)"
        ),
        "old": threshold, "new": ratio,
        "relative_change": None, "direction": "higher",
    }


def async_speedup_gate(record: dict, threshold: float) -> dict | None:
    """In-record async-federation gate: bench.py's ``async`` leg records
    the run's simulated-clock speedup of deadline rounds over the
    wait-for-everyone synchronous counterfactual
    (``async_speedup_ratio``, computed from the same arrival draws —
    a deterministic program property). A ratio below ``threshold``
    means deadline rounds stopped beating sync under the documented
    80/20 population — a regression regardless of the old record.
    Judged ABSOLUTELY like the other in-record gates (near a fixed
    operating point, a relative gate would flap). None when the leg is
    absent or the floor holds."""
    ratio = get_path(record, "async.async_speedup_ratio")
    if ratio is None or ratio >= threshold:
        return None
    return {
        "metric": "async.async_speedup_ratio",
        "description": (
            "simulated-clock speedup of async deadline rounds vs the "
            "sync wait-for-everyone counterfactual (>= 1.0 means async "
            "pays)"
        ),
        "old": threshold, "new": ratio,
        "relative_change": None, "direction": "higher",
    }


def stream_overlap_gate(record: dict, threshold: float) -> dict | None:
    """In-record streamed-residency gate: bench.py's ``stream`` leg
    records, at its largest swept population, the fraction of host->HBM
    cohort-upload time the double-buffered prefetch hid behind compute
    (``overlap_ratio``, parallel/streaming.py). A ratio below
    ``threshold`` means the prefetch stopped overlapping — per-dispatch
    transfers have gone synchronous and the streamed mode's cost model
    no longer holds. Judged ABSOLUTELY like the other in-record gates
    (the ratio sits near a fixed operating point, where a relative gate
    would flap). None when the leg is absent or the floor holds."""
    ratio = get_path(record, "stream.overlap_ratio")
    if ratio is None or ratio >= threshold:
        return None
    return {
        "metric": "stream.overlap_ratio",
        "description": (
            "fraction of streamed-residency host->HBM upload time hidden "
            "behind compute at the largest swept population (prefetch "
            "must overlap)"
        ),
        "old": threshold, "new": ratio,
        "relative_change": None, "direction": "higher",
    }


def stream_cohort_rate_gate(record: dict, threshold: float) -> dict | None:
    """In-record streamed-throughput gate: bench.py's ``stream`` leg
    records, at its largest swept population under the
    fastest-supported ``participation_sampler`` (hashed when swept —
    ops/sampling.py), the steady cohort training rate
    (``cohort_rate``, cohort·rounds/s). A rate below ``threshold``
    means the million-client stream leg went host-bound again — the
    regression the O(cohort) sampler exists to prevent (the exact
    replay's O(N log N) draw measured ~1 s/round at N=1e6,
    docs/PERFORMANCE.md § Streamed client state). Judged ABSOLUTELY
    like the other in-record gates (an absolute floor in the record's
    own units, the PR 4/5/7 precedent). None when the leg is absent or
    the floor holds."""
    rate = get_path(record, "stream.cohort_rate")
    if rate is None or rate >= threshold:
        return None
    return {
        "metric": "stream.cohort_rate",
        "description": (
            "steady cohort·rounds/s of the streamed-residency leg at "
            "its largest swept population, fastest-supported sampler "
            "(the million-client leg must stay model-bound, not "
            "host-bound on the cohort draw)"
        ),
        "old": threshold, "new": rate,
        "relative_change": None, "direction": "higher",
    }


def valuation_corr_gate(record: dict, threshold: float) -> dict | None:
    """In-record valuation-fidelity gate: bench.py's ``valuation`` leg
    measures, on the small-N graded-quality differential config, the
    Spearman correlation between the streaming client-valuation vector
    and the cumulative truncated-GTG audit SVs
    (telemetry/valuation.py). A correlation below ``threshold`` means
    the cheap always-on estimator stopped tracking exact Shapley — its
    per-round signal is no longer a trustworthy contribution ranking —
    a regression regardless of the old record. Judged ABSOLUTELY (the
    PR 4/5/8 precedent: the correlation sits near a fixed operating
    point ~0.85-0.9, where a relative gate would flap). None when the
    leg is absent or the floor holds."""
    corr = get_path(record, "valuation.audit_spearman")
    if corr is None or corr >= threshold:
        return None
    return {
        "metric": "valuation.audit_spearman",
        "description": (
            "Spearman correlation of the streaming client-valuation "
            "vector vs cumulative exact GTG audit SVs on the "
            "graded-quality differential (estimator fidelity floor)"
        ),
        "old": threshold, "new": corr,
        "relative_change": None, "direction": "higher",
    }


def sweep_amortization_gate(record: dict, threshold: float) -> dict | None:
    """In-record sweep-engine gate: bench.py's ``sweep`` leg measures,
    within one bench run, the wall-clock of N serial solo runs against
    the same N points executed as one vmapped seed fleet
    (``sweep_amortization_ratio`` = serial wall / fleet wall; the fleet
    pays one compile and one dispatch per round for every experiment).
    A ratio below ``threshold`` means the fleet stopped amortizing —
    compile or dispatch overhead is being re-paid per point — a
    regression regardless of the old record. Judged ABSOLUTELY like the
    other in-record gates (the ratio sits at a fixed operating point set
    by the compile/run balance, where a relative gate would flap; the
    PR 4/5/10 precedent). None when the leg is absent or the floor
    holds."""
    ratio = get_path(record, "sweep.sweep_amortization_ratio")
    if ratio is None or ratio >= threshold:
        return None
    return {
        "metric": "sweep.sweep_amortization_ratio",
        "description": (
            "serial-solo vs vmapped-fleet wall-clock ratio for the "
            "same sweep points (>= 2.0 means the fleet at least halves "
            "the sweep's wall — the acceptance operating point)"
        ),
        "old": threshold, "new": ratio,
        "relative_change": None, "direction": "higher",
    }


def gtg_scaling_gate(record: dict, threshold: float) -> dict | None:
    """In-record GTG mesh-scaling gate: bench.py's ``gtg`` leg runs a
    D=2-vs-D=1 subset-eval throughput microbench through the real
    ``_SubsetEvaluator`` (the mesh-sharded GTG walk's fused-call shape,
    algorithms/shapley.py) and records ``gtg_scaling_ratio`` — ONLY when
    the host had >= 2 usable cores, so the number is an honest
    device-parallel measurement. A ratio below ``threshold`` means
    sharding the walk stopped paying (lost replication short-circuit,
    accidental collective, per-call placement cost) — a regression
    regardless of the old record. Judged ABSOLUTELY (the PR 4/5/8 gate
    precedent: the ratio sits near a fixed operating point where a
    relative gate would flap). None when the leg is absent (including
    the unarmed 1-core case) or the floor holds."""
    ratio = get_path(record, "gtg.gtg_scaling_ratio")
    if ratio is None or ratio >= threshold:
        return None
    return {
        "metric": "gtg.gtg_scaling_ratio",
        "description": (
            "D=2/D=1 subset-eval throughput of the mesh-sharded GTG "
            "walk (two devices must keep buying walk throughput)"
        ),
        "old": threshold, "new": ratio,
        "relative_change": None, "direction": "higher",
    }


def mhost_cohort_rate_gate(record: dict, threshold: float) -> dict | None:
    """In-record multihost stream-throughput gate: bench.py's ``mhost``
    leg runs the 2-process distributed-shard-store N-sweep (streamed +
    hashed cohorts, owner-sharded assembly with the spill exchange —
    parallel/streaming.DistributedCohortStreamer) and records
    ``mhost_cohort_rate`` (cohort·rounds/s at the largest population) —
    ONLY when the host had >= 2 usable cores, so the two processes'
    compute genuinely overlaps (the PR 14 arming precedent: a 1-core
    cgroup records the honest number under ``cohort_rate`` unarmed). A
    rate below ``threshold`` means the distributed data plane stopped
    keeping the composed run model-bound (exchange on the critical
    path, lost prefetch overlap, per-round placement cost) — a
    regression regardless of the old record. Judged ABSOLUTELY as an
    in-record floor; None when the leg is absent (including unarmed) or
    the floor holds."""
    rate = get_path(record, "mhost.mhost_cohort_rate")
    if rate is None or rate >= threshold:
        return None
    return {
        "metric": "mhost.mhost_cohort_rate",
        "description": (
            "steady cohort-rounds/s of the 2-process distributed "
            "shard store at the largest swept population (the "
            "owner-sharded data plane must stay off the critical path)"
        ),
        "old": threshold, "new": rate,
        "relative_change": None, "direction": "higher",
    }


def churn_overhead_gate(record: dict, threshold: float) -> dict | None:
    """In-record open-world-churn gate: bench.py's ``churn`` leg runs a
    10x population-growth ``population='dynamic'`` run against the same
    program static (both streamed + hashed + sampled — the composition
    dynamic populations require) and records ``churn_overhead_ratio``,
    the dynamic-vs-static median round-time ratio minus one
    (robustness/population.py). A ratio above ``threshold`` means the
    registration stream (masked draw, event draws, store growth, drift
    mutation, synchronous gather) stopped riding the round at marginal
    cost — a regression regardless of the old record. Judged ABSOLUTELY
    (the PR 4 overhead-gate precedent: the ratio sits near a fixed small
    operating point, where a relative gate would flap). None when the
    leg is absent or the ceiling holds."""
    ratio = get_path(record, "churn.churn_overhead_ratio")
    if ratio is None or ratio <= threshold:
        return None
    return {
        "metric": "churn.churn_overhead_ratio",
        "description": (
            "round-time overhead of the 10x-growth dynamic-population "
            "run vs the same program static (registration stream must "
            "ride the round at marginal cost)"
        ),
        "old": threshold, "new": ratio,
        "relative_change": None, "direction": "lower",
    }


def span_overhead_gate(record: dict, threshold: float) -> dict | None:
    """In-record span-trace overhead gate: bench.py's ``spans`` leg
    re-runs the headline program with ``span_trace='on'``
    (telemetry/spans.py) and records the on-vs-off round-time
    ``overhead_ratio`` within that single bench run. A ratio above
    ``threshold`` means the recorder stopped being cheap enough to leave
    on in production — a regression regardless of the old record.
    Judged ABSOLUTELY (the PR 4/5 precedent: the ratio hovers near
    zero, where relative changes are pure noise). None when the leg is
    absent or the ceiling holds."""
    ratio = get_path(record, "spans.overhead_ratio")
    if ratio is None or ratio <= threshold:
        return None
    return {
        "metric": "spans.overhead_ratio",
        "description": (
            "span_trace=on round-time overhead vs the same run's "
            "off-mode headline (the distributed tracer must stay cheap "
            "enough to leave on)"
        ),
        "old": threshold, "new": ratio,
        "relative_change": None, "direction": "lower",
    }


def model_drift_gate(record: dict, threshold: float) -> list[dict]:
    """In-record cost-model drift gate: bench.py's ``costmodel`` leg
    records, per proxied program, the roofline model's predicted-vs-
    measured per-round ratio (``model_error_ratio``,
    telemetry/costmodel.py). A ratio outside the absolute band
    ``1.0 +- threshold`` means the analytic model no longer describes
    the program it prices — capacity projections built on it are stale
    and the efficiency factors need a deliberate, documented refit
    (docs/PERFORMANCE.md § Predicted pod-scale cost). Judged as an
    absolute BAND, never relatively (the ratio sits near a fixed
    operating point, where a relative gate would flap); returns one
    regression entry per out-of-band program, empty when the leg is
    absent or every ratio holds."""
    out = []
    for program in ("cnn", "flagship"):
        ratio = get_path(record, f"costmodel.{program}.model_error_ratio")
        if ratio is None or abs(ratio - 1.0) <= threshold:
            continue
        out.append({
            "metric": f"costmodel.{program}.model_error_ratio",
            "description": (
                f"roofline-predicted vs measured per-round time of the "
                f"{program} program (must stay within 1.0 +- "
                f"{threshold:g}; refit the model deliberately, see "
                "docs/PERFORMANCE.md)"
            ),
            "old": threshold, "new": ratio,
            "relative_change": None, "direction": "near-1.0",
        })
    return out


def _fmt(entry: dict) -> str:
    rel = entry["relative_change"]
    rel_s = f"{rel:+.1%}" if rel is not None else "n/a"
    return (
        f"  {entry['metric']:<34} {entry['old']:>12g} -> "
        f"{entry['new']:>12g}  ({rel_s}, {entry['direction']} is better) "
        f"— {entry['description']}"
    )


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Regression gate over two bench.py JSON records"
    )
    ap.add_argument("old", help="baseline bench JSON file")
    ap.add_argument("new", help="candidate bench JSON file")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="relative regression tolerance (default 0.05)")
    ap.add_argument("--force", action="store_true",
                    help="compare even when provenance says incomparable")
    ap.add_argument("--stats-overhead-threshold", type=float, default=0.10,
                    help="max tolerated client_stats=on round-time overhead "
                         "ratio in the NEW record (default 0.10)")
    ap.add_argument("--batch-amortization-threshold", type=float,
                    default=0.95,
                    help="min tolerated rounds_per_dispatch K-vs-1 rate "
                         "ratio in the NEW record's round_batch leg "
                         "(default 0.95 — batching must at least break "
                         "even, modulo run noise)")
    ap.add_argument("--async-speedup-threshold", type=float, default=1.0,
                    help="min tolerated simulated-clock speedup in the "
                         "NEW record's async leg (default 1.0 — deadline "
                         "rounds must at least match the synchronous "
                         "counterfactual; the ratio is deterministic, "
                         "not wall-clock noise)")
    ap.add_argument("--stream-overlap-threshold", type=float, default=0.5,
                    help="min tolerated prefetch overlap ratio in the NEW "
                         "record's stream leg at its largest population "
                         "(default 0.5 — at least half the host->HBM "
                         "upload time must hide behind compute)")
    ap.add_argument("--stream-cohort-rate-threshold", type=float,
                    default=900.0,
                    help="min tolerated cohort*rounds/s in the NEW "
                         "record's stream leg at its largest population, "
                         "fastest-supported sampler (default 900 — ~3x "
                         "the r07 host-bound 328 c*r/s N=1e6 CPU "
                         "baseline the hashed sampler retired; "
                         "docs/PERFORMANCE.md § Streamed client state)")
    ap.add_argument("--sweep-amortization-threshold", type=float,
                    default=2.0,
                    help="min tolerated serial-vs-fleet wall ratio in the "
                         "NEW record's sweep leg (default 2.0 — an "
                         "8-point vmapped seed fleet must finish in under "
                         "half the wall of 8 serial solo runs; compile "
                         "paid once is the multiplier)")
    ap.add_argument("--valuation-corr-threshold", type=float, default=0.8,
                    help="min tolerated streaming-valuation vs GTG-audit "
                         "Spearman correlation in the NEW record's "
                         "valuation leg (default 0.8 — the estimator "
                         "must keep tracking exact Shapley on the "
                         "differential config; measured operating point "
                         "~0.85-0.9)")
    ap.add_argument("--gtg-scaling-threshold", type=float, default=1.5,
                    help="min tolerated D=2/D=1 subset-eval throughput "
                         "ratio in the NEW record's gtg leg (default 1.5 "
                         "— sharding the GTG walk over two devices must "
                         "buy at least 1.5x; bench records the key only "
                         "on hosts that can honestly measure it, i.e. "
                         ">= 2 usable cores)")
    ap.add_argument("--mhost-cohort-rate-threshold", type=float,
                    default=200.0,
                    help="min tolerated steady cohort-rounds/s in the NEW "
                         "record's mhost leg at its largest population "
                         "(default 200 — the 2-process distributed shard "
                         "store must keep the composed streamed run off "
                         "the host-bound floor; bench records the gated "
                         "key only on hosts with >= 2 usable cores, "
                         "where the two processes' compute genuinely "
                         "overlaps)")
    ap.add_argument("--churn-overhead-threshold", type=float, default=0.10,
                    help="max tolerated dynamic-vs-static round-time "
                         "overhead ratio in the NEW record's churn leg "
                         "(default 0.10 — the 10x population-growth "
                         "registration stream must ride the round at "
                         "marginal cost)")
    ap.add_argument("--span-overhead-threshold", type=float, default=0.05,
                    help="max tolerated span_trace=on round-time overhead "
                         "ratio in the NEW record's spans leg (default "
                         "0.05 — the distributed tracer's cheap-enough-"
                         "to-leave-on promise)")
    ap.add_argument("--model-drift-threshold", type=float, default=0.35,
                    help="max tolerated |model_error_ratio - 1| in the NEW "
                         "record's costmodel leg, per program (default "
                         "0.35: the band covers the documented ~25% "
                         "device-vs-wall host-side share on the cnn "
                         "headline plus fit residuals)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable comparison as JSON")
    args = ap.parse_args(argv)

    with open(args.old) as f:
        old = json.load(f)
    with open(args.new) as f:
        new = json.load(f)

    reason = check_comparable(old, new)
    if reason and not args.force:
        print(f"REFUSED: {reason}", file=sys.stderr)
        return 2
    if old.get("config_hash") is None or new.get("config_hash") is None:
        print(
            "note: at least one record predates the provenance stamp "
            "(bench schema v1); comparability is not verifiable",
            file=sys.stderr,
        )

    result = compare_records(old, new, threshold=args.threshold)
    for gate in (
        overhead_gate(new, args.stats_overhead_threshold),
        batch_amortization_gate(new, args.batch_amortization_threshold),
        async_speedup_gate(new, args.async_speedup_threshold),
        stream_overlap_gate(new, args.stream_overlap_threshold),
        stream_cohort_rate_gate(new, args.stream_cohort_rate_threshold),
        sweep_amortization_gate(new, args.sweep_amortization_threshold),
        valuation_corr_gate(new, args.valuation_corr_threshold),
        gtg_scaling_gate(new, args.gtg_scaling_threshold),
        churn_overhead_gate(new, args.churn_overhead_threshold),
        mhost_cohort_rate_gate(new, args.mhost_cohort_rate_threshold),
        span_overhead_gate(new, args.span_overhead_threshold),
    ):
        if gate is not None:
            result["regressions"].append(gate)
    result["regressions"].extend(
        model_drift_gate(new, args.model_drift_threshold)
    )
    if args.json:
        print(json.dumps(result, indent=2))
    else:
        for title, key in (("REGRESSIONS", "regressions"),
                           ("improvements", "improvements"),
                           ("within threshold", "unchanged")):
            if result[key]:
                print(f"{title}:")
                for entry in result[key]:
                    print(_fmt(entry))
        if result["skipped"]:
            print("skipped (absent on one side): "
                  + ", ".join(e["metric"] for e in result["skipped"]))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
