"""Experiment: per-leaf (padded) vs flat-vector SGD update + SR store.

The flagship round profile shows the per-client momentum-SGD update +
hash-SR bf16 store fusions running at ~280 GB/s on 64-channel param leaves
([C,3,3,64,64]: the (8,128) tiling pads lanes 64->128) vs ~700 GB/s on
512-channel leaves. A single flat [C, P] parameter vector has no lane
padding. This measures both formulations of one update step at flagship
scale (C=40 clients x ResNet-18).

Usage: python scripts/exp_flat_update.py [n_chain]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from distributed_learning_simulator_tpu.models.registry import (
    get_model,
    init_params,
)
from distributed_learning_simulator_tpu.parallel.engine import _sr_tree_to_bf16


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 30
    C = 40
    model = get_model("resnet18", num_classes=10)
    p0 = init_params(model, np.zeros((1, 32, 32, 3), np.float32), seed=0)

    def stack(tree, fill):
        return jax.tree_util.tree_map(
            lambda l: jnp.full((C,) + l.shape, fill, jnp.bfloat16), tree
        )

    ptree, mtree, gtree = stack(p0, 1.0), stack(p0, 0.0), stack(p0, 0.01)
    flat = lambda t: jnp.concatenate(  # noqa: E731
        [jnp.reshape(l, (C, -1)) for l in jax.tree_util.tree_leaves(t)], axis=1
    )
    pflat, mflat, gflat = flat(ptree), flat(mtree), flat(gtree)
    print("flat shape", pflat.shape)

    def upd_tree(p, m, g, salt):
        m2 = jax.tree_util.tree_map(
            lambda mm, gg: 0.9 * mm.astype(jnp.float32)
            + gg.astype(jnp.float32),
            m, g,
        )
        summed = jax.tree_util.tree_map(
            lambda pp, mm: pp.astype(jnp.float32) - 0.1 * mm, p, m2
        )
        p2, salt = _sr_tree_to_bf16(summed, salt)
        m2 = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), m2)
        return p2, m2, salt

    def upd_flat(p, m, g, salt):
        m2 = 0.9 * m.astype(jnp.float32) + g.astype(jnp.float32)
        summed = p.astype(jnp.float32) - 0.1 * m2
        p2, salt = _sr_tree_to_bf16(summed, salt)
        return p2, m2.astype(jnp.bfloat16), salt

    def chain(fn, p, m, g):
        out = fn(p, m, g, jnp.uint32(1))
        jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
        o = out
        t0 = time.perf_counter()
        for _ in range(n):
            o = fn(o[0], o[1], g, o[2])
        jax.device_get(jax.tree_util.tree_leaves(o)[0].ravel()[:1])
        return (time.perf_counter() - t0) / n

    f_tree = jax.jit(upd_tree, donate_argnums=(0, 1))
    f_flat = jax.jit(upd_flat, donate_argnums=(0, 1))
    t_tree = chain(f_tree, ptree, mtree, gtree)
    t_flat = chain(f_flat, pflat, mflat, gflat)
    print(f"tree update+SR: {t_tree*1e3:6.2f} ms/step-chunk")
    print(f"flat update+SR: {t_flat*1e3:6.2f} ms/step-chunk")


if __name__ == "__main__":
    main()
