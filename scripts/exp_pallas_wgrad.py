"""Prototype: Pallas weight-grad for the W-folded stage-1 conv.

XLA's autodiff of the packed-kernel conv computes the grad for ALL
[3,3,128,128] packed slots (4x the live parameters) and then unpacks —
~55 ms x 4 convs per round at ~370 GB/s. This kernel computes the
UNPACKED [3,3,64,64] grad directly as 18 rank-2 MXU contractions —
true-FLOPs only, one unpacked write.

Mosaic constraints shaped the design (each was hit as a compile error):
  * no value reshapes across tiled dims -> operate on (B*H'*W')-flattened
    rows with the 128 channels as lanes;
  * dynamic/static sublane slice offsets must be multiples of 8 -> pad
    W' 18 -> 24 so the dy row-offsets are (dy-1)*24, and move the +-1
    column shifts into 3 HOST-prepared shifted copies of g (the grid's
    second dimension picks the copy; only 2 of 18 taps need the +-1
    copies);
  * 18 fully-unrolled slices overflow the VMEM stack -> one (x, g_v)
    pair resident per grid step, slices of constant length MP.
Zero padding on both operands makes every invalid term vanish by
multiplication (padding rows of g contribute 0; a shifted x partner in
padding multiplies 0), so there are no masks.

Usage: python scripts/exp_pallas_wgrad.py [n_chain] [chunk]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_learning_simulator_tpu.models.resnet import (
    pack_folded_kernel,
)

B, H, WF, C = 25, 32, 16, 64  # folded stage-1 shape, cin = cout = C
HP, WP = H + 2, 24  # zero-padded spatial dims; WP=24 keeps row
MP = B * HP * WP    # offsets (dy-1)*WP a multiple of 8 (Mosaic sublanes)
HALO = WP  # >= max |row offset|; multiple of 8

# (dx, sx) pairs grouped by the column shift v of their tap
# (u = sx + dx - 1 = 2v + tx).
_BY_V = {-1: [], 0: [], 1: []}
for _dx in range(3):
    for _sx in range(2):
        _v, _tx = divmod(_sx + _dx - 1, 2)
        _BY_V[_v].append((_dx, _sx, _tx))


TILES = 5  # batch-dim tiles; B=25 -> 5 elements per tile
BT = B // TILES
MT = BT * HP * WP  # rows per tile (multiple of 8)
MTH = MT + 2 * HALO  # haloed tile rows


def _wgrad_kernel(x_ref, g_ref, out_ref):
    """x_ref: [1, 1, MTH, 2C] bf16 (pre-haloed tile); g_ref:
    [1, 1, 1, MT, 2C] bf16 (this grid step's v-shifted copy, same tile);
    out_ref: [1, 3, 3, C, C] f32, accumulated over the (v, tile) grid."""
    vstep = pl.program_id(1)
    tstep = pl.program_id(2)

    @pl.when(jnp.logical_and(vstep == 0, tstep == 0))
    def _():
        out_ref[0] = jnp.zeros((3, 3, C, C), jnp.float32)

    for v_idx, v in enumerate((-1, 0, 1)):
        @pl.when(vstep == v_idx)
        def _(v=v):
            for dx, sx, tx in _BY_V[v]:
                bm = g_ref[0, 0, 0, :, sx * C:(sx + 1) * C]
                for dy in range(3):
                    start = HALO + (dy - 1) * WP
                    a = x_ref[0, 0, start:start + MT, tx * C:(tx + 1) * C]
                    part = jax.lax.dot_general(
                        a, bm,
                        dimension_numbers=(((0,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    out_ref[0, dy, dx] = out_ref[0, dy, dx] + part


def _prep(xf, gf):
    """Host-side packing: zero-pad spatially (W' to 24), flatten rows,
    build overlapping pre-haloed x tiles and the 3 column-shifted g
    copies (dy shifts never cross a batch element, so tiles on batch
    boundaries are self-contained up to their zero halos)."""
    n = xf.shape[0]
    pad = ((0, 0), (0, 0), (1, 1), (1, 7), (0, 0))
    xp = jnp.pad(xf, pad)  # [n, B, HP, WP, 2C]
    gp = jnp.pad(gf, pad)
    x2 = jnp.pad(
        xp.reshape(n, MP, 2 * C), ((0, 0), (HALO, HALO), (0, 0))
    )
    xt = jnp.stack(
        [x2[:, t * MT:t * MT + MTH] for t in range(TILES)], axis=1
    )  # [n, TILES, MTH, 2C]
    # g shifted by +v along W': term x[.., J+v] g[.., J] == x[.., J']
    # g[.., J'-v] — shift g so every tap slice is a pure row offset.
    # roll is safe: the wrapped-around columns are zero padding.
    g3 = jnp.stack(
        [jnp.roll(gp, shift=v, axis=3) for v in (-1, 0, 1)], axis=1
    ).reshape(n, 3, TILES, MT, 2 * C)
    return xt, g3


def pallas_wgrad(xf, gf, interpret=False):
    """xf/gf: [N, B, H, WF, 2C] -> d_w [N, 3, 3, C, C] f32."""
    n = xf.shape[0]
    xt, g3 = _prep(xf, gf)
    return pl.pallas_call(
        _wgrad_kernel,
        grid=(n, 3, TILES),
        in_specs=[
            pl.BlockSpec((1, 1, MTH, 2 * C),
                         lambda c, v, t: (c, t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1, MT, 2 * C),
                         lambda c, v, t: (c, v, t, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 3, 3, C, C),
                               lambda c, v, t: (c, 0, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, 3, 3, C, C), jnp.float32),
        interpret=interpret,
    )(xt, g3)


def autodiff_wgrad(xf, gf):
    """Reference: d_w via the packed conv's autodiff (what runs today)."""

    def conv_one(xc, w):
        wp = pack_folded_kernel(w)
        return jax.lax.conv_general_dilated(
            xc, wp, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    def loss(w, xc, gc):
        return jnp.sum((conv_one(xc, w) * gc).astype(jnp.float32))

    w0 = jnp.zeros((3, 3, C, C), jnp.bfloat16)
    return jax.vmap(
        lambda xc, gc: jax.grad(loss)(w0, xc, gc)
    )(xf, gf)


def timeit(fn, args, n):
    out = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(out)[0].ravel()[:1])
    acc = out
    t0 = time.perf_counter()
    for _ in range(n):
        acc = fn(*args)
    jax.device_get(jax.tree_util.tree_leaves(acc)[0].ravel()[:1])
    return (time.perf_counter() - t0) / n


def main():
    n_chain = int(sys.argv[1]) if len(sys.argv) > 1 else 15
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    key = jax.random.key(0)
    xf = jax.random.normal(key, (chunk, B, H, WF, 2 * C), jnp.bfloat16)
    gf = jax.random.normal(jax.random.fold_in(key, 1),
                           (chunk, B, H, WF, 2 * C), jnp.bfloat16)

    d_ref = jax.jit(autodiff_wgrad)(xf, gf)
    d_pal = jax.jit(pallas_wgrad)(xf, gf)
    err = jnp.max(jnp.abs(d_ref.astype(jnp.float32) - d_pal))
    rel = err / jnp.max(jnp.abs(d_ref.astype(jnp.float32)))
    print(f"max |err| {float(err):.4f} (rel {float(rel):.2e})")

    t_ref = timeit(jax.jit(autodiff_wgrad), (xf, gf), n_chain)
    t_pal = timeit(jax.jit(pallas_wgrad), (xf, gf), n_chain)
    print(f"autodiff packed wgrad: {t_ref*1e3:7.2f} ms | pallas unpacked: "
          f"{t_pal*1e3:7.2f} ms")


if __name__ == "__main__":
    main()
