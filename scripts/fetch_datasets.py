#!/usr/bin/env python3
"""Download MNIST / CIFAR-10 and save them in the registry's .npz layout.

Usage (needs network; the training container is offline and falls back to
the synthetic surrogate instead):

    python scripts/fetch_datasets.py [--data_dir /root/data] [mnist cifar10]

Writes ``<data_dir>/<name>.npz`` with keys x_train/y_train/x_test/y_test —
exactly what ``data/registry.py`` looks for before falling back. Images are
stored uint8; the registry rescales to [0, 1] on load.
"""

from __future__ import annotations

import argparse
import os
import sys
import urllib.request

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from distributed_learning_simulator_tpu.data.formats import (  # noqa: E402
    cifar10_arrays,
    mnist_arrays,
)

MNIST_BASE = "https://ossci-datasets.s3.amazonaws.com/mnist/"
MNIST_FILES = [
    "train-images-idx3-ubyte.gz",
    "train-labels-idx1-ubyte.gz",
    "t10k-images-idx3-ubyte.gz",
    "t10k-labels-idx1-ubyte.gz",
]
CIFAR10_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"


def _get(url: str) -> bytes:
    print(f"  downloading {url}")
    with urllib.request.urlopen(url, timeout=120) as r:
        return r.read()


def fetch_mnist(data_dir: str) -> str:
    arrays = mnist_arrays(*(_get(MNIST_BASE + f) for f in MNIST_FILES))
    path = os.path.join(data_dir, "mnist.npz")
    np.savez_compressed(path, **arrays)
    return path


def fetch_cifar10(data_dir: str) -> str:
    arrays = cifar10_arrays(_get(CIFAR10_URL))
    path = os.path.join(data_dir, "cifar10.npz")
    np.savez_compressed(path, **arrays)
    return path


FETCHERS = {"mnist": fetch_mnist, "cifar10": fetch_cifar10}


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("names", nargs="*",
                    help="datasets to fetch (default: all of "
                    f"{sorted(FETCHERS)})")
    ap.add_argument("--data_dir",
                    default=os.environ.get("DLS_DATA_DIR", "/root/data"))
    args = ap.parse_args()
    names = args.names or sorted(FETCHERS)
    unknown = sorted(set(names) - set(FETCHERS))
    if unknown:
        ap.error(f"unknown dataset(s) {unknown}; known: {sorted(FETCHERS)}")
    os.makedirs(args.data_dir, exist_ok=True)
    for name in names:
        print(f"fetching {name} ...")
        path = FETCHERS[name](args.data_dir)
        with np.load(path) as z:
            shapes = {k: z[k].shape for k in z.files}
        print(f"  wrote {path}: {shapes}")


if __name__ == "__main__":
    main()
