"""Decompose the bench round's device time: train+aggregate vs eval.

Times the jitted round program and the jitted eval program separately by
chaining N dispatches and fetching one scalar at the end (the tunnel makes
any per-step fetch a ~100 ms RTT; see docs/PERFORMANCE.md "Profiling
method").

Usage: python scripts/profile_round.py [model] [chunk] [dtype] [evalbatch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp


def main():
    model_name = sys.argv[1] if len(sys.argv) > 1 else "resnet18"
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 50
    dtype = sys.argv[3] if len(sys.argv) > 3 else "float32"
    eval_batch = int(sys.argv[4]) if len(sys.argv) > 4 else 10000

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.data.registry import get_dataset
    from distributed_learning_simulator_tpu.factory import get_algorithm
    from distributed_learning_simulator_tpu.models.registry import (
        get_model,
        init_params,
    )
    from distributed_learning_simulator_tpu.parallel.engine import (
        make_decoder,
        make_eval_fn,
        make_optimizer,
        make_reshaper,
        pad_eval_set,
    )
    from distributed_learning_simulator_tpu.simulator import build_client_data

    config = ExperimentConfig(
        dataset_name="cifar10", model_name=model_name,
        distributed_algorithm="fed", worker_number=1000, round=3, epoch=1,
        learning_rate=0.1, momentum=0.9, batch_size=25, log_level="WARNING",
        eval_batch_size=eval_batch, client_chunk_size=chunk,
        local_compute_dtype=dtype,
    )
    dataset = get_dataset(config.dataset_name, seed=0)
    client_data = build_client_data(config, dataset)
    eval_batches = tuple(
        jnp.asarray(a) for a in pad_eval_set(
            dataset.x_test, dataset.y_test, config.eval_batch_size,
            flatten=True,
        )
    )
    model = get_model(config.model_name, num_classes=dataset.num_classes)
    params = init_params(model, dataset.x_train[:1], seed=0)
    optimizer = make_optimizer("SGD", 0.1, momentum=0.9)
    algorithm = get_algorithm("fed", config)
    reshaper = make_reshaper(dataset.x_test.shape[1:])
    evaluate = jax.jit(make_eval_fn(model.apply, preprocess=reshaper))
    algorithm.prepare(model.apply, make_eval_fn(model.apply,
                                                preprocess=reshaper))
    round_fn = algorithm.make_round_fn(
        model.apply, optimizer, client_data.n_clients,
        preprocess=make_decoder(client_data.sample_shape),
    )
    round_jit = jax.jit(round_fn)

    cx = jnp.asarray(client_data.x)
    cy = jnp.asarray(client_data.y)
    cmask = jnp.asarray(client_data.mask)
    sizes = jnp.asarray(client_data.sizes)
    key = jax.random.key(1)

    def time_rounds(n):
        g = params
        t0 = time.perf_counter()
        for i in range(n):
            g, _, aux = round_jit(g, None, cx, cy, cmask, sizes,
                                  jax.random.fold_in(key, i))
        jax.device_get(aux["mean_client_loss"])
        return (time.perf_counter() - t0) / n

    def time_eval(n):
        t0 = time.perf_counter()
        for _ in range(n):
            m = evaluate(params, *eval_batches)
        jax.device_get(m["accuracy"])
        return (time.perf_counter() - t0) / n

    time_rounds(1)  # compile
    time_eval(1)
    tr = time_rounds(5)
    te = time_eval(5)
    print(f"model={model_name} chunk={chunk} dtype={dtype} "
          f"eval_batch={eval_batch}")
    print(f"train+aggregate: {tr*1000:.0f} ms/round")
    print(f"eval:            {te*1000:.0f} ms/round")
    print(f"sum:             {(tr+te)*1000:.0f} ms/round "
          f"(target < 3000 ms for 333.3 c·r/s)")


if __name__ == "__main__":
    main()
