"""Relative mesh-scaling measurement on virtual CPU devices.

Runs the same FedAvg workload with the client axis sharded over 1/2/4/8
virtual host-CPU devices and reports steady-state round time + relative
efficiency. This validates that the sharded program's collectives and
layouts don't introduce scaling overhead — it does NOT measure real chip
speedup (all virtual devices share the same host cores, so ideal scaling
here is flat round time per device count only when host cores are not
saturated; the honest signal is the absence of super-linear SLOWDOWN from
resharding/collective overhead as the mesh grows).

The mesh points run as ONE sweep (sweep/engine.py, scheduled strategy)
in ONE worker interpreter instead of the old one-subprocess-per-mesh
loop: the config-hash grouping runs each mesh size through its own
program, and — the ISSUE 11 small fix — each point's warmup
(trace+compile, previously re-paid per invocation and silently dropped
by the ``history[1:]`` slice) is counted once per program and recorded
explicitly in the table's ``warmup`` column.

Usage:  python scripts/measure_scaling.py [clients] [rounds]
Writes a markdown table to stdout (pasted into docs/PERFORMANCE.md).
"""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

_WORKER = textwrap.dedent("""
    import json, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.sweep import SweepSpec, run_sweep

    clients, rounds, chunk = (int(a) for a in sys.argv[1:4])
    meshes = [int(m) for m in sys.argv[4].split(",")]
    base = ExperimentConfig(
        dataset_name="synthetic",
        model_name="mlp",
        distributed_algorithm="fed",
        worker_number=clients,
        round=rounds + 1,
        epoch=2,
        learning_rate=0.1,
        batch_size=16,
        n_train=clients * 32,
        n_test=256,
        log_level="ERROR",
        dataset_args={"difficulty": 0.5},
        client_chunk_size=chunk if chunk > 0 else None,
        compilation_cache_dir=None,
    )
    # One scheduled sweep over the mesh axis: every point shares the
    # same data/partition, each mesh size compiles its own program
    # (different sharding = honestly different program) and records its
    # warmup explicitly instead of silently dropping round 0.
    spec = SweepSpec(
        base,
        [{"mesh_devices": m if m > 1 else None} for m in meshes],
        strategy="scheduled",
    )
    out = run_sweep(spec)
    for m, p in zip(meshes, out["points"]):
        steady = [h["round_seconds"] for h in p["history"][1:]]
        print(json.dumps({
            "mesh": m,
            "round_s": sum(steady) / len(steady),
            "warmup_s": p["warmup_seconds"],
            "acc": p["final_accuracy"],
        }))
""")


def measure(meshes: list[int], clients: int, rounds: int,
            chunk: int) -> list[dict]:
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.join(os.path.dirname(__file__), "..")
    proc = subprocess.run(
        [sys.executable, "-c", _WORKER, str(clients), str(rounds),
         str(chunk), ",".join(str(m) for m in meshes)],
        cwd=repo, env=env, capture_output=True, text=True, timeout=3600,
    )
    if proc.returncode != 0:
        raise RuntimeError(proc.stderr[-2000:])
    import json

    return [
        json.loads(line)
        for line in proc.stdout.strip().splitlines()[-len(meshes):]
    ]


def main():
    clients = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 5
    chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    rows = measure([1, 2, 4, 8], clients, rounds, chunk)
    base = rows[0]["round_s"]
    print(f"\n{clients} clients x {rounds} rounds, mlp, synthetic data, "
          f"chunk={chunk or 'none'} (virtual CPU devices; one sweep, "
          f"warmup recorded per program)\n")
    print("| mesh devices | round (s) | warmup (s) | vs 1-device "
          "| accuracy |")
    print("|---|---|---|---|---|")
    for r in rows:
        print(f"| {r['mesh']} | {r['round_s']:.3f} "
              f"| {r['warmup_s']:.2f} "
              f"| {base / r['round_s']:.2f}x | {r['acc']:.3f} |")


if __name__ == "__main__":
    main()
