"""Experiment: W-folded per-client conv for the 64-channel stage.

[B, H, W, 64] tensors tile (8,128) with lanes padded 64->128 (2x HBM
inflation; round profile: 64-ch ops run ~278 GB/s vs ~660 for 128+ ch).
Folding W-pairs into channels — [B, H, W/2, 128], a PURE reshape of the
trailing dims — fills the lanes. A stride-1 3x3 conv on the folded form is
a 3x3 conv with a packed kernel W'[dy, V, (tx,ci), (sx,co)] built from the
original w[3,3,cin,cout] by 6 static slice-assignments (50% fill -> 2x
MXU FLOPs, paid from idle MXU capacity since the op is bandwidth-bound).
Exact math, exact autodiff (the packing transpose discards zero-slot
grads).

Measures per-client (vmapped weights) fwd+bwd: normal conv vs folded conv,
plus the 3-channel stem conv cost for reference.

Usage: python scripts/exp_folded_conv.py [n_chain] [chunk] [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

# The SHIPPED packer (trailing-dim concats; an earlier .at[].set build
# measured ~20 GB/s dynamic-update-slice chains) — import it so re-running
# this experiment measures the code path the model actually runs.
from distributed_learning_simulator_tpu.models.resnet import (  # noqa: E402
    pack_folded_kernel,
)


def timeit(fn, args, n):
    out = fn(*args)
    jax.device_get(out)
    t0 = time.perf_counter()
    acc = out
    for _ in range(n):
        acc = acc + fn(*args)
    jax.device_get(acc)
    return (time.perf_counter() - t0) / n


def main():
    n_chain = int(sys.argv[1]) if len(sys.argv) > 1 else 15
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 25
    hw, cin, cout = 32, 64, 64

    key = jax.random.key(0)
    kx, kw, kg = jax.random.split(key, 3)
    x = jax.random.normal(kx, (chunk, batch, hw, hw, cin), jnp.bfloat16)
    w = jax.random.normal(kw, (chunk, 3, 3, cin, cout), jnp.bfloat16)
    g = jax.random.normal(kg, (chunk, batch, hw, hw, cout), jnp.bfloat16)

    def conv_one(xc, wc):
        return jax.lax.conv_general_dilated(
            xc, wc, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    # --- A: baseline vmapped conv on [B,32,32,64] --------------------------
    def loss_a(w_, x_):
        y = jax.vmap(conv_one)(x_, w_)
        return jnp.sum((y * g).astype(jnp.float32))

    def run_a(w_, x_):
        gw, gx = jax.grad(loss_a, argnums=(0, 1))(w_, x_)
        return jnp.sum(gw.astype(jnp.float32)) + jnp.sum(
            gx.astype(jnp.float32)
        )

    t_a = timeit(jax.jit(run_a), (w, x), n_chain)

    # --- B: folded conv on [B,32,16,128] -----------------------------------
    xf = x.reshape(chunk, batch, hw, hw // 2, 2 * cin)
    gf = g.reshape(chunk, batch, hw, hw // 2, 2 * cout)

    def loss_b(w_, xf_):
        wp = jax.vmap(pack_folded_kernel)(w_)
        y = jax.vmap(conv_one)(xf_, wp)
        return jnp.sum((y * gf).astype(jnp.float32))

    def run_b(w_, xf_):
        gw, gx = jax.grad(loss_b, argnums=(0, 1))(w_, xf_)
        return jnp.sum(gw.astype(jnp.float32)) + jnp.sum(
            gx.astype(jnp.float32)
        )

    t_b = timeit(jax.jit(run_b), (w, xf), n_chain)

    # --- correctness: folded == normal -------------------------------------
    y_a = jax.jit(lambda: jax.vmap(conv_one)(x, w))()
    y_b = jax.jit(
        lambda: jax.vmap(conv_one)(xf, jax.vmap(pack_folded_kernel)(w))
    )()
    err = jnp.max(jnp.abs(
        y_a.reshape(y_b.shape).astype(jnp.float32) - y_b.astype(jnp.float32)
    ))

    # --- C: stem conv [B,32,32,3] -> 64 (lane-pad 3->128 on input) ---------
    xs = jax.random.normal(kx, (chunk, batch, hw, hw, 3), jnp.bfloat16)
    ws = jax.random.normal(kw, (chunk, 3, 3, 3, cout), jnp.bfloat16)

    def loss_c(w_, x_):
        y = jax.vmap(conv_one)(x_, w_)
        return jnp.sum((y * g).astype(jnp.float32))

    def run_c(w_, x_):
        gw, gx = jax.grad(loss_c, argnums=(0, 1))(w_, x_)
        return jnp.sum(gw.astype(jnp.float32)) + jnp.sum(
            gx.astype(jnp.float32)
        )

    t_c = timeit(jax.jit(run_c), (ws, xs), n_chain)

    print(f"stage1 conv fwd+bwd: normal {t_a*1e3:7.2f} ms | folded "
          f"{t_b*1e3:7.2f} ms | max |err| {float(err):.4f}")
    print(f"stem conv (3ch in) fwd+bwd: {t_c*1e3:7.2f} ms")


if __name__ == "__main__":
    main()
