"""Chaos harness: prove bit-exact crash resume (docs/ROBUSTNESS.md).

Runs a reference simulation to completion, then re-runs it killing the
process at a chosen round — an in-process injected crash
(``robustness.chaos.InjectedCrash``) and a subprocess ``SIGKILL`` (no
cleanup, no ``finally`` blocks: the torn-state variant a real preemption
produces) — resumes via ``config.resume``, and asserts the stitched
``history`` is **bit-identical** to the uninterrupted run. The workload
deliberately exercises both resume-sensitive RNG streams: cohort sampling
(``participation_fraction < 1``) and an active dropout failure model, so
the assertion covers the checkpointed ``rng_key`` chain end to end. A
third variant sends ``SIGTERM`` (the TPU preemption notice): the run must
finish its in-flight round, write a final checkpoint, log
``preempted at round N``, exit cleanly — and the resumed tail must again
match the reference bit-for-bit.

Usage::

    python scripts/chaos_resume.py                    # all variants; JSON verdict
    python scripts/chaos_resume.py --rounds 8 --crash-round 3
    python scripts/chaos_resume.py --variants inprocess,sigkill

Internal: ``--child --config '<json>'`` runs one crashed leg in a fresh
interpreter (the parent sets ``DLS_CRASH_AT_ROUND`` / ``DLS_CRASH_KIND``
in its environment). Exit status: 0 when every requested variant is
bit-identical, 1 otherwise.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Wall-clock fields legitimately differ between runs; everything else in a
# history record must match bit-for-bit.
VOLATILE_KEYS = ("round_seconds",)

# Wall-clock fields inside the schema-v5 ``stream`` sub-object
# (client_residency='streamed' workloads, e.g. the dynamic-population
# variant): transfer/draw TIMINGS differ run to run, while the byte
# counts and sampler name must still match bit-for-bit.
STREAM_VOLATILE_KEYS = (
    "h2d_seconds", "hidden_seconds", "overlap_ratio", "sample_ms",
    "d2h_seconds",
)


def _pin_platform():
    """Honor JAX_PLATFORMS even where a sitecustomize force-registers a
    TPU plugin ahead of it (the test environment's quirk)."""
    platform = os.environ.get("JAX_PLATFORMS")
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)


def strip_volatile(records: list[dict]) -> list[dict]:
    out = []
    for r in records:
        r = {k: v for k, v in r.items() if k not in VOLATILE_KEYS}
        if isinstance(r.get("stream"), dict):
            r["stream"] = {
                k: v for k, v in r["stream"].items()
                if k not in STREAM_VOLATILE_KEYS
            }
        out.append(r)
    return out


def normalize(records: list[dict]) -> list[dict]:
    """JSON-roundtrip in-memory records so they compare exactly against
    records read back from metrics.jsonl (Python floats survive the trip
    bit-for-bit via repr; this only normalizes types like np.bool_)."""
    return json.loads(json.dumps(strip_volatile(records)))


def read_metrics_jsonl(log_root: str) -> list[dict]:
    """Per-round records a (possibly SIGKILLed) run managed to flush."""
    paths = sorted(glob.glob(os.path.join(log_root, "**", "metrics.jsonl"),
                             recursive=True))
    if not paths:
        return []
    records = []
    for path in paths:
        with open(path) as f:
            records.extend(json.loads(line) for line in f if line.strip())
    return strip_volatile(records)


def chaos_config(workdir: str, leg: str, rounds: int, **overrides):
    """The harness workload: small enough for CPU CI, adversarial enough
    to cover every resume-sensitive stream (client sampling + dropout
    failure model + quorum telemetry in every record)."""
    from distributed_learning_simulator_tpu.config import ExperimentConfig

    kw = dict(
        dataset_name="synthetic",
        model_name="mlp",
        distributed_algorithm="fed",
        worker_number=6,
        round=rounds,
        epoch=1,
        learning_rate=0.1,
        batch_size=32,
        n_train=384,
        n_test=128,
        log_level="INFO",
        dataset_args={"difficulty": 0.5},
        participation_fraction=0.5,
        failure_mode="dropout",
        failure_prob=0.3,
        failure_correlation=0.5,
        min_survivors=1,
        log_root=os.path.join(workdir, leg, "log"),
    )
    kw.update(overrides)
    return ExperimentConfig(**kw)


def run_straight(workdir: str, rounds: int) -> list[dict]:
    from distributed_learning_simulator_tpu.simulator import run_simulation

    result = run_simulation(chaos_config(workdir, "straight", rounds))
    return normalize(result["history"])


def _crash_env(crash_round: int, kind: str) -> dict:
    env = dict(os.environ)
    env["DLS_CRASH_AT_ROUND"] = str(crash_round)
    env["DLS_CRASH_KIND"] = kind
    return env


def run_crashed_inprocess(config, crash_round: int) -> list[dict]:
    """Crashed leg, same interpreter: InjectedCrash unwinds run_simulation;
    the records it already flushed come back from metrics.jsonl."""
    from distributed_learning_simulator_tpu.robustness.chaos import (
        InjectedCrash,
    )
    from distributed_learning_simulator_tpu.simulator import run_simulation

    os.environ["DLS_CRASH_AT_ROUND"] = str(crash_round)
    os.environ["DLS_CRASH_KIND"] = "raise"
    try:
        run_simulation(config)
    except InjectedCrash:
        pass
    else:
        raise AssertionError("injected crash did not fire")
    finally:
        os.environ.pop("DLS_CRASH_AT_ROUND", None)
        os.environ.pop("DLS_CRASH_KIND", None)
    return read_metrics_jsonl(config.log_root)


def run_crashed_subprocess(config, crash_round: int, kind: str):
    """Crashed leg in a fresh interpreter; returns the CompletedProcess
    (callers assert the death signal / clean exit) — flushed records are
    read from the leg's metrics.jsonl afterwards."""
    payload = vars(config)
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--config", json.dumps(payload)],
        env=_crash_env(crash_round, kind),
        capture_output=True, text=True, timeout=600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )


def run_resumed(config) -> list[dict]:
    import dataclasses

    from distributed_learning_simulator_tpu.simulator import run_simulation

    result = run_simulation(dataclasses.replace(config, resume=True))
    return normalize(result["history"])


def stitch_and_compare(straight, crashed, resumed) -> dict:
    """Stitch crashed-prefix + resumed-tail and diff against the straight
    run. The resumed run's first record tells where the prefix ends (a
    crash between checkpoints replays the rounds after the newest valid
    checkpoint — those must reproduce bit-identically too)."""
    if not resumed:
        return {"bit_identical": False, "error": "resumed run has no rounds"}
    start = resumed[0]["round"]
    stitched = [r for r in crashed if r["round"] < start] + resumed
    mismatches = [
        {"round": a.get("round"), "straight": a, "stitched": b}
        for a, b in zip(straight, stitched) if a != b
    ]
    if len(straight) != len(stitched):
        mismatches.append({
            "error": f"length {len(stitched)} != straight {len(straight)}"
        })
    return {
        "bit_identical": not mismatches,
        "resume_start_round": start,
        "rounds": len(straight),
        "mismatches": mismatches[:3],
    }


def run_variant(variant: str, workdir: str, rounds: int,
                crash_round: int, straight) -> dict:
    cfg = chaos_config(
        workdir, variant, rounds,
        checkpoint_dir=os.path.join(workdir, variant, "ckpt"),
        # Off the crash round's cadence on purpose: resume must also
        # bit-exactly REPLAY the rounds between the newest checkpoint and
        # the crash.
        checkpoint_every=2 if variant == "sigkill" else 1,
    )
    if variant == "inprocess":
        crashed = run_crashed_inprocess(cfg, crash_round)
    elif variant == "sigkill":
        proc = run_crashed_subprocess(cfg, crash_round, "sigkill")
        if proc.returncode != -signal.SIGKILL:
            return {
                "bit_identical": False,
                "error": f"child exited {proc.returncode}, expected "
                         f"-SIGKILL; stderr tail: {proc.stderr[-500:]}",
            }
        crashed = read_metrics_jsonl(cfg.log_root)
    elif variant == "sigterm":
        proc = run_crashed_subprocess(cfg, crash_round, "sigterm")
        if proc.returncode != 0:
            return {
                "bit_identical": False,
                "error": f"child exited {proc.returncode}, expected a clean "
                         f"0; stderr tail: {proc.stderr[-500:]}",
            }
        # With round pipelining the SIGTERM lands while the NEXT round is
        # already in flight; "finish the in-flight round" then completes
        # crash_round + 1, and that is the round the log names.
        if "preempted at round" not in proc.stderr:
            return {
                "bit_identical": False,
                "error": "child log lacks the 'preempted at round N' line",
            }
        crashed = read_metrics_jsonl(cfg.log_root)
    else:
        raise ValueError(f"unknown variant {variant!r}")
    verdict = stitch_and_compare(straight, crashed, run_resumed(cfg))
    verdict["crashed_rounds_flushed"] = len(crashed)
    return verdict


def child_main(config_json: str) -> None:
    _pin_platform()
    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    result = run_simulation(ExperimentConfig(**json.loads(config_json)))
    print(json.dumps({
        "preempted_at": result["preempted_at"],
        "rounds": len(result["history"]),
    }))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--rounds", type=int, default=6)
    parser.add_argument("--crash-round", type=int, default=3)
    parser.add_argument("--variants", default="inprocess,sigkill,sigterm")
    parser.add_argument("--workdir", default=None,
                        help="scratch dir (default: a fresh temp dir)")
    parser.add_argument("--child", action="store_true")
    parser.add_argument("--config", default=None)
    args = parser.parse_args(argv)
    if args.child:
        child_main(args.config)
        return 0
    _pin_platform()
    if not 0 <= args.crash_round < args.rounds - 1:
        parser.error("--crash-round must leave at least one round to resume")
    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_resume_")
    straight = run_straight(workdir, args.rounds)
    report = {"workdir": workdir, "rounds": args.rounds,
              "crash_round": args.crash_round, "variants": {}}
    ok = True
    for variant in args.variants.split(","):
        verdict = run_variant(
            variant.strip(), workdir, args.rounds, args.crash_round, straight
        )
        report["variants"][variant.strip()] = verdict
        ok = ok and verdict.get("bit_identical", False)
    report["ok"] = ok
    print(json.dumps(report, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
