"""GTG-Shapley at the north-star population: N=1000, cnn_tpu.

One honest data point (VERDICT r3 weak #7): wall-clock per round,
permutations per round, subset evaluations, and peak HBM. Run on the real
chip:

    python scripts/measure_gtg_scale.py [rounds] [eval_samples] [eval_chunk] \
        [max_permutations] [eval_dtype] [prefix_mode]

(eval_chunk default 64 — the chunk-16-vs-64 comparison in
docs/PERFORMANCE.md § Scale validation is reproduced by passing 16/64.
max_permutations 0 = auto cap max(500, 2N); pass 1000 to reproduce the
round-4 one-iteration fixed-budget measurement. eval_dtype default
bfloat16 = the resolved GTG default; pass float32 for the r4
configuration. prefix_mode default cumsum = config default; pass masked
for the pre-round-6 per-prefix aggregation path — the cumsum-vs-masked
before/after in docs/PERFORMANCE.md § GTG at scale is this script run
twice.)

The last line is ONE JSON record tracking the converged-GTG round cost —
the wall-clock of the final non-round-truncated round (round 0 carries the
XLA compile, so prefer rounds >= 2 and read the steady-state value).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    eval_samples = int(sys.argv[2]) if len(sys.argv) > 2 else 2000
    eval_chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    max_perms = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    eval_dtype = sys.argv[5] if len(sys.argv) > 5 else "bfloat16"
    prefix_mode = sys.argv[6] if len(sys.argv) > 6 else "cumsum"

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    config = ExperimentConfig(
        dataset_name="cifar10", model_name="cnn_tpu",
        distributed_algorithm="GTG_shapley_value", worker_number=1000,
        round=rounds, epoch=1, learning_rate=0.1, momentum=0.9,
        batch_size=25, client_chunk_size=250, eval_batch_size=10000,
        shapley_eval_samples=eval_samples, shapley_eval_chunk=eval_chunk,
        gtg_max_permutations=max_perms or None,
        shapley_eval_dtype=eval_dtype, gtg_prefix_mode=prefix_mode,
        log_level="INFO",
    )
    t0 = time.perf_counter()
    result = run_simulation(config, setup_logging=False)
    wall = time.perf_counter() - t0
    for h in result["history"]:
        print(
            f"round {h['round']}: {h['round_seconds']:.1f}s total, "
            f"acc={h['test_accuracy']:.4f}, "
            f"permutations={h.get('gtg_permutations')}, "
            f"subset_evals={h.get('gtg_subset_evals')}, "
            f"converged={h.get('gtg_converged')}"
        )
    print(f"total wall: {wall:.1f}s for {rounds} rounds")
    # The shared telemetry probe (telemetry/memory.py): graceful None on
    # backends without memory stats, same helper the simulator's per-round
    # watermark and budget model use.
    from distributed_learning_simulator_tpu.telemetry import peak_hbm_bytes

    peak = peak_hbm_bytes()
    if peak:
        print(f"peak HBM: {peak / 2**30:.2f} GiB")
    else:
        print("memory_stats unavailable on this backend")

    # Tracked metric (ISSUE 1): converged-GTG round wall-clock — the same
    # record shape bench.py's ``gtg`` sub-object emits (one shared
    # constructor, utils/reporting.py, so the two numbers stay comparable).
    from distributed_learning_simulator_tpu.utils.reporting import (
        gtg_round_record,
    )

    rec = gtg_round_record(
        result["history"],
        clients=1000, prefix_mode=prefix_mode, eval_samples=eval_samples,
        eval_chunk=eval_chunk, eval_dtype=eval_dtype,
        peak_hbm_gib=round(peak / 2**30, 2) if peak else None,
    )
    if rec is not None:
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
