"""GTG-Shapley at the north-star population: N=1000, cnn_tpu.

One honest data point (VERDICT r3 weak #7): wall-clock per round,
permutations per round, subset evaluations, and peak HBM. Run on the real
chip:

    python scripts/measure_gtg_scale.py [rounds] [eval_samples] [eval_chunk] \
        [max_permutations] [eval_dtype]

(eval_chunk default 64 — the chunk-16-vs-64 comparison in
docs/PERFORMANCE.md § Scale validation is reproduced by passing 16/64.
max_permutations 0 = auto cap max(500, 2N); pass 1000 to reproduce the
round-4 one-iteration fixed-budget measurement. eval_dtype default
bfloat16 = config default; pass float32 for the r4 configuration.)
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    eval_samples = int(sys.argv[2]) if len(sys.argv) > 2 else 2000
    eval_chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    max_perms = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    eval_dtype = sys.argv[5] if len(sys.argv) > 5 else "bfloat16"

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    config = ExperimentConfig(
        dataset_name="cifar10", model_name="cnn_tpu",
        distributed_algorithm="GTG_shapley_value", worker_number=1000,
        round=rounds, epoch=1, learning_rate=0.1, momentum=0.9,
        batch_size=25, client_chunk_size=250, eval_batch_size=10000,
        shapley_eval_samples=eval_samples, shapley_eval_chunk=eval_chunk,
        gtg_max_permutations=max_perms or None,
        shapley_eval_dtype=eval_dtype,
        log_level="INFO",
    )
    t0 = time.perf_counter()
    result = run_simulation(config, setup_logging=False)
    wall = time.perf_counter() - t0
    for h in result["history"]:
        print(
            f"round {h['round']}: {h['round_seconds']:.1f}s total, "
            f"acc={h['test_accuracy']:.4f}, "
            f"permutations={h.get('gtg_permutations')}"
        )
    print(f"total wall: {wall:.1f}s for {rounds} rounds")
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        if peak:
            print(f"peak HBM: {peak / 2**30:.2f} GiB")
        else:
            print(f"memory_stats keys: {sorted(stats)}")
    except Exception as e:  # plugin may not expose memory stats
        print(f"memory_stats unavailable: {e}")


if __name__ == "__main__":
    main()
