"""GTG-Shapley at the north-star population: N=1000, cnn_tpu.

One honest data point (VERDICT r3 weak #7): wall-clock per round,
permutations per round, subset evaluations, and peak HBM. Run on the real
chip:

    python scripts/measure_gtg_scale.py [rounds] [eval_samples] [eval_chunk] \
        [max_permutations] [eval_dtype] [prefix_mode] [mesh_devices]

(eval_chunk default 64 — the chunk-16-vs-64 comparison in
docs/PERFORMANCE.md § Scale validation is reproduced by passing 16/64.
max_permutations 0 = auto cap max(500, 2N); pass 1000 to reproduce the
round-4 one-iteration fixed-budget measurement. eval_dtype default
bfloat16 = the resolved GTG default; pass float32 for the r4
configuration. prefix_mode default cumsum = config default; pass masked
for the pre-round-6 per-prefix aggregation path — the cumsum-vs-masked
before/after in docs/PERFORMANCE.md § GTG at scale is this script run
twice. mesh_devices default 1 = the serial walk; > 1 shards the GTG
walk's subset/group axis over that many devices — bit-identical SVs,
permutation counts and eval counts (algorithms/shapley.py) — and the
JSON then records BOTH sides: the sharded ``gtg_round_seconds`` plus a
serial reference run (``gtg_round_seconds_serial``/``shard_speedup``;
GTG_SCALE_SERIAL=0 skips the reference). CPU runs use the established
idiom from tests/test_multichip.py —
``XLA_FLAGS=--xla_force_host_platform_device_count=D`` — which this
script applies itself when JAX_PLATFORMS=cpu and the flag is absent.)

The last line is ONE JSON record tracking the converged-GTG round cost —
the wall-clock of the final non-round-truncated round (round 0 carries the
XLA compile, so prefer rounds >= 2 and read the steady-state value) —
plus, since ISSUE 9, the other side of the 100x gap in the same
artifact: the streaming valuation estimator's per-round cost
(``estimator_round_seconds``, a fed run of the same workload with the
always-on signal; ``estimator_gap_ratio`` = walk/estimator), its
fidelity against the run's own exact SVs
(``valuation_spearman``/``valuation_pearson``), and the cross-round
memo reuse rate (``gtg_memo_hit_rate``; the run sets
``gtg_cross_round_memo=True``). ``GTG_SCALE_ESTIMATOR_ROUNDS=0`` skips
the estimator-cost run.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    eval_samples = int(sys.argv[2]) if len(sys.argv) > 2 else 2000
    eval_chunk = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    max_perms = int(sys.argv[4]) if len(sys.argv) > 4 else 0
    eval_dtype = sys.argv[5] if len(sys.argv) > 5 else "bfloat16"
    prefix_mode = sys.argv[6] if len(sys.argv) > 6 else "cumsum"
    mesh_devices = int(sys.argv[7]) if len(sys.argv) > 7 else 1

    if (
        mesh_devices > 1
        and os.environ.get("JAX_PLATFORMS", "").lower() == "cpu"
        and "--xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")
    ):
        # The tests/test_multichip.py CPU idiom, applied before the first
        # jax import below: virtual host devices stand in for the mesh.
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={mesh_devices}"
        )

    from distributed_learning_simulator_tpu.config import ExperimentConfig
    from distributed_learning_simulator_tpu.simulator import run_simulation

    config = ExperimentConfig(
        dataset_name="cifar10", model_name="cnn_tpu",
        distributed_algorithm="GTG_shapley_value", worker_number=1000,
        round=rounds, epoch=1, learning_rate=0.1, momentum=0.9,
        batch_size=25, client_chunk_size=250, eval_batch_size=10000,
        shapley_eval_samples=eval_samples, shapley_eval_chunk=eval_chunk,
        gtg_max_permutations=max_perms or None,
        shapley_eval_dtype=eval_dtype, gtg_prefix_mode=prefix_mode,
        mesh_devices=mesh_devices if mesh_devices > 1 else None,
        # Streaming valuation rides the same run (ISSUE 9): its per-round
        # cost is measured against these GTG rounds below, and its final
        # vector correlates against the run's own exact per-round SVs —
        # the 100x-gap trajectory (walk seconds vs estimator seconds vs
        # fidelity) tracked in ONE artifact.
        client_stats="on", client_valuation="on",
        # Cross-round memo (ROADMAP item 4b): measure the cross-round
        # utility REUSE rate at scale. Under the default cumsum prefix
        # mode hits do not avoid device work (the walker streams every
        # position for its carries — shapley.SubsetMemo); pass
        # prefix_mode=masked to measure the realized call savings.
        gtg_cross_round_memo=True,
        log_level="INFO",
    )
    t0 = time.perf_counter()
    result = run_simulation(config, setup_logging=False)
    wall = time.perf_counter() - t0
    for h in result["history"]:
        print(
            f"round {h['round']}: {h['round_seconds']:.1f}s total, "
            f"acc={h['test_accuracy']:.4f}, "
            f"permutations={h.get('gtg_permutations')}, "
            f"subset_evals={h.get('gtg_subset_evals')}, "
            f"converged={h.get('gtg_converged')}"
        )
    print(f"total wall: {wall:.1f}s for {rounds} rounds")
    # The shared telemetry probe (telemetry/memory.py): graceful None on
    # backends without memory stats, same helper the simulator's per-round
    # watermark and budget model use.
    from distributed_learning_simulator_tpu.telemetry import peak_hbm_bytes

    peak = peak_hbm_bytes()
    if peak:
        print(f"peak HBM: {peak / 2**30:.2f} GiB")
    else:
        print("memory_stats unavailable on this backend")

    # Tracked metric (ISSUE 1): converged-GTG round wall-clock — the same
    # record shape bench.py's ``gtg`` sub-object emits (one shared
    # constructor, utils/reporting.py, so the two numbers stay comparable).
    from distributed_learning_simulator_tpu.utils.reporting import (
        gtg_round_record,
    )

    # Streaming-estimator cross-check (ISSUE 9): the run carried the
    # always-on valuation vector alongside the exact walks, so the 100x
    # gap's two sides land in ONE artifact — the walk's wall-clock above,
    # the estimator's per-round cost below, and the fidelity correlation
    # between the final streaming vector and the run's own mean exact
    # SVs (rounds whose walk actually ran; truncated rounds carry none).
    import numpy as np

    from distributed_learning_simulator_tpu.telemetry.valuation import (
        pearson_corr,
        spearman_corr,
    )

    n = 1000
    sv_rounds = [
        np.asarray([sv[i] for i in range(n)])
        for r, sv in sorted(result["algorithm"].shapley_values.items())
        if any(sv.values())
    ]
    corr_sp = corr_pe = None
    if sv_rounds:
        sv_mean = np.mean(np.stack(sv_rounds), axis=0)
        values = result["valuation_state"].values
        corr_sp = spearman_corr(values, sv_mean)
        corr_pe = pearson_corr(values, sv_mean)

    # The estimator's own per-round cost: the SAME workload as a plain
    # fed run with the streaming valuation on — the round the always-on
    # signal actually rides in production. GTG_SCALE_ESTIMATOR_ROUNDS=0
    # skips (e.g. when only re-measuring the walk).
    import dataclasses

    est_rounds = int(os.environ.get("GTG_SCALE_ESTIMATOR_ROUNDS", "3"))
    est_round_s = None
    if est_rounds > 0:
        fed_config = dataclasses.replace(
            config, distributed_algorithm="fed", round=est_rounds + 1,
            gtg_cross_round_memo=False, log_level="WARNING",
        )
        fed_result = run_simulation(fed_config, setup_logging=False)
        steady = [
            h["round_seconds"] for h in fed_result["history"][1:]
        ]
        if steady:
            est_round_s = sorted(steady)[len(steady) // 2]

    # Sharded-vs-serial reference (mesh_devices > 1): the same workload's
    # serial walk, so the JSON carries BOTH sides of the scaling claim in
    # one artifact (sharded == serial is bit-identical by contract, so
    # only the wall-clock differs). GTG_SCALE_SERIAL=0 skips.
    serial_round_s = None
    if mesh_devices > 1 and os.environ.get("GTG_SCALE_SERIAL", "1") != "0":
        serial_result = run_simulation(
            dataclasses.replace(
                config, mesh_devices=None, log_level="WARNING",
            ),
            setup_logging=False,
        )
        serial_rec = gtg_round_record(serial_result["history"])
        if serial_rec is not None:
            serial_round_s = serial_rec["value"]

    rec = gtg_round_record(
        result["history"],
        clients=n, prefix_mode=prefix_mode, eval_samples=eval_samples,
        eval_chunk=eval_chunk, eval_dtype=eval_dtype,
        mesh_devices=mesh_devices,
        peak_hbm_gib=round(peak / 2**30, 2) if peak else None,
        # Cross-round memo reuse at scale (ROADMAP item 4b).
        gtg_memo_hit_rate=result["gtg_memo_hit_rate"],
        # Estimator-vs-GTG fidelity + the estimator's round cost: the
        # gap ratio is the ~100x the streaming signal exists to bridge.
        valuation_spearman=(
            None if corr_sp is None else round(corr_sp, 4)
        ),
        valuation_pearson=(
            None if corr_pe is None else round(corr_pe, 4)
        ),
        estimator_round_seconds=(
            None if est_round_s is None else round(est_round_s, 3)
        ),
    )
    if rec is not None and est_round_s:
        rec["estimator_gap_ratio"] = round(rec["value"] / est_round_s, 1)
    if rec is not None and serial_round_s is not None:
        rec["gtg_round_seconds_serial"] = serial_round_s
        if rec["value"]:
            rec["shard_speedup"] = round(serial_round_s / rec["value"], 2)
    if rec is not None:
        print(json.dumps(rec))


if __name__ == "__main__":
    main()
