"""Stitch per-host span journals into one cross-host timeline.

Each host of a ``span_trace='on'`` run writes its own
``spans_<host_id>.jsonl`` (telemetry/spans.py): spans stamped with that
host's PRIVATE monotonic clock. This tool is the read side — it aligns
every host onto host 0's wall clock and emits one merged view:

    python scripts/trace_timeline.py DIR [DIR|FILE ...]
        [--out trace.json] [--json] [--host H]

* positional args — artifact/span directories (globbed for
  ``spans_*.jsonl``) or explicit journal files. Pass every host's
  journal (a shared ``span_dir`` makes this one directory).
* ``--out trace.json`` — write a Chrome trace-event file: load it in
  Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``; one
  process row per host, ``main`` + ``prefetch`` threads.
* ``--json`` — machine-readable summary on stdout instead of text.
* ``--host H`` — restrict the text/JSON summary to one host (the trace
  file always carries every host: a one-host timeline can't show skew).

Clock alignment: every journal header carries back-to-back
``epoch_wall``/``epoch_mono`` anchors plus ``clock_offset_s`` — this
host's wall clock minus host 0's, estimated once at the
``jax.distributed`` init barrier (parallel/multihost.py
``estimate_clock_alignment``) — and ``clock_uncertainty_s``, the
measured barrier RTT that bounds the estimate. A monotonic stamp t
aligns as::

    aligned = (t - epoch_mono) + epoch_wall - clock_offset_s

so all hosts land on host 0's wall timeline, good to ~the barrier RTT
(microseconds on a LAN; the summary prints the uncertainty so nobody
over-reads sub-RTT skews).

The summary computes, per round, ``barrier_skew_ms`` per barrier (the
max-minus-min host arrival the wait spans measured) and names the
slowest host — on a wait span the SHORTEST wait marks the host everyone
else waited for. Run totals give each host's DCN-wait vs busy split and
its share of the summed busy time (critical-path share). Unmatched
``open`` lines, ``inflight`` lines, and ``flight`` markers become the
postmortem section: what each host was doing when it died or was told
to stop (docs/OBSERVABILITY.md § Distributed tracing).

Deliberately imports nothing heavy (no jax, no telemetry package): the
journals are plain JSONL and this must run on a laptop holding only the
artifact files. Self-tested jax-free in tests/test_spans.py.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

#: Span categories counted as BUSY time (vs dcn_wait, which is idle
#: time spent waiting for other hosts at a barrier).
BUSY_CATS = ("phase", "dcn", "io", "stream", "round")


def find_journals(paths: list[str]) -> list[str]:
    """Expand directories to their spans_*.jsonl files; keep files."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "spans_*.jsonl"))))
        elif os.path.exists(p):
            out.append(p)
        else:
            raise FileNotFoundError(p)
    # De-dup while preserving order (a dir + an explicit file may overlap).
    seen: set[str] = set()
    uniq = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def load_journal(path: str) -> dict:
    """Parse one host journal into {header, spans, events, opens,
    inflight, flights}. Tolerates a torn final line (SIGKILL mid-write)."""
    header = None
    spans: list[dict] = []
    events: list[dict] = []
    opens: dict[int, dict] = {}
    inflight: list[dict] = []
    flights: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail line from a killed process
            kind = rec.get("kind")
            if kind == "header":
                header = rec
            elif kind == "span":
                spans.append(rec)
                opens.pop(rec.get("id"), None)
            elif kind == "event":
                events.append(rec)
            elif kind == "open":
                opens[rec.get("id")] = rec
            elif kind == "inflight":
                inflight.append(rec)
                opens.pop(rec.get("id"), None)
            elif kind == "flight":
                flights.append(rec)
    if header is None:
        raise ValueError(f"{path}: no header line — not a span journal")
    return {
        "path": path,
        "header": header,
        "spans": spans,
        "events": events,
        # Opens never matched by a span/inflight line: the process died
        # inside them with no cleanup — the hard-kill postmortem signal.
        "unmatched_opens": list(opens.values()),
        "inflight": inflight,
        "flights": flights,
    }


def aligner(header: dict):
    """Monotonic stamp -> host-0 wall seconds (see module docstring)."""
    epoch_mono = float(header["epoch_mono"])
    epoch_wall = float(header["epoch_wall"])
    offset = float(header.get("clock_offset_s", 0.0))

    def align(t: float) -> float:
        return (t - epoch_mono) + epoch_wall - offset

    return align


# ----------------------------------------------------------------------
# Chrome trace-event emission


def chrome_trace(journals: list[dict]) -> dict:
    """Merge journals into a Chrome trace-event JSON object (perfetto/
    chrome://tracing loadable). One process per host; the streaming
    prefetch worker gets its own thread row."""
    out: list[dict] = []
    t0 = None  # earliest aligned stamp across hosts -> trace origin
    prepared = []
    for j in journals:
        align = aligner(j["header"])
        host = int(j["header"]["host_id"])
        last = None
        rows = []
        for s in j["spans"]:
            ts = align(s["t0"])
            rows.append(("X", s, ts, float(s.get("dur", 0.0))))
            last = ts + float(s.get("dur", 0.0)) if last is None else max(
                last, ts + float(s.get("dur", 0.0)))
        for e in j["events"]:
            ts = align(e["t"])
            rows.append(("i", e, ts, 0.0))
            last = ts if last is None else max(last, ts)
        # A span the host died inside: draw it to the last stamp the
        # journal saw so the kill moment is visible on the timeline.
        for s in j["unmatched_opens"] + j["inflight"]:
            ts = align(s["t0"])
            end = last if last is not None and last > ts else ts
            rows.append(("X", {**s, "inflight": True}, ts, end - ts))
        prepared.append((host, j, rows))
        for _, _, ts, _ in rows:
            t0 = ts if t0 is None else min(t0, ts)
    if t0 is None:
        t0 = 0.0
    for host, j, rows in prepared:
        out.append({"ph": "M", "name": "process_name", "pid": host,
                    "tid": 0, "args": {"name": f"host {host}"}})
        out.append({"ph": "M", "name": "thread_name", "pid": host,
                    "tid": 0, "args": {"name": "main"}})
        out.append({"ph": "M", "name": "thread_name", "pid": host,
                    "tid": 1, "args": {"name": "prefetch"}})
        for ph, rec, ts, dur in rows:
            tid = 1 if rec.get("cat") == "stream" else 0
            ev = {
                "name": rec.get("name", "?"),
                "cat": rec.get("cat", "?"),
                "ph": ph,
                "ts": round((ts - t0) * 1e6, 3),
                "pid": host,
                "tid": tid,
            }
            if ph == "X":
                ev["dur"] = round(dur * 1e6, 3)
            else:
                ev["s"] = "t"
            args = dict(rec.get("attrs") or {})
            if rec.get("round") is not None:
                args["round"] = rec["round"]
            if rec.get("inflight"):
                args["inflight"] = True
            if args:
                ev["args"] = args
            out.append(ev)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


# ----------------------------------------------------------------------
# summary analytics


def _wait_groups(journals: list[dict]) -> dict:
    """(round, barrier name) -> [(host, wait dur s, skew_ms attr)]."""
    groups: dict[tuple, list] = {}
    for j in journals:
        host = int(j["header"]["host_id"])
        for s in j["spans"]:
            if s.get("cat") != "dcn_wait":
                continue
            key = (s.get("round"), s.get("name"))
            attrs = s.get("attrs") or {}
            groups.setdefault(key, []).append(
                (host, float(s.get("dur", 0.0)), attrs.get("skew_ms"))
            )
    return groups


def summarize(journals: list[dict], host: int | None = None) -> dict:
    """The cross-host analytics block: per-round barrier skews with the
    slowest host named, per-host busy/wait totals + critical-path share,
    and the postmortem section."""
    hosts = []
    totals: dict[int, dict] = {}
    for j in journals:
        h = j["header"]
        hid = int(h["host_id"])
        busy = 0.0
        wait = 0.0
        by_cat: dict[str, float] = {}
        for s in j["spans"]:
            cat = s.get("cat", "?")
            dur = float(s.get("dur", 0.0))
            by_cat[cat] = by_cat.get(cat, 0.0) + dur
            if cat == "dcn_wait":
                wait += dur
            elif cat in BUSY_CATS and cat != "round":
                # 'round' is the envelope span; counting it would double
                # count the phases nested inside it.
                busy += dur
        totals[hid] = {"busy_s": busy, "dcn_wait_s": wait,
                       "by_cat": by_cat}
        hosts.append({
            "host_id": hid,
            "n_hosts": int(h.get("n_hosts", 1)),
            "pid": h.get("pid"),
            "journal": j["path"],
            "clock_offset_s": h.get("clock_offset_s", 0.0),
            "clock_uncertainty_s": h.get("clock_uncertainty_s", 0.0),
            "spans": len(j["spans"]),
            "events": len(j["events"]),
        })
    busy_sum = sum(t["busy_s"] for t in totals.values())
    for hid, t in totals.items():
        denom = t["busy_s"] + t["dcn_wait_s"]
        t["wait_fraction"] = round(t["dcn_wait_s"] / denom, 4) if denom else 0.0
        t["critical_path_share"] = (
            round(t["busy_s"] / busy_sum, 4) if busy_sum else 0.0
        )
        t["busy_s"] = round(t["busy_s"], 6)
        t["dcn_wait_s"] = round(t["dcn_wait_s"], 6)
        t["by_cat"] = {k: round(v, 6) for k, v in sorted(t["by_cat"].items())}

    rounds: dict[int, dict] = {}
    for (rnd, name), members in sorted(
        _wait_groups(journals).items(),
        key=lambda kv: (kv[0][0] is None, kv[0]),
    ):
        skews = [m[2] for m in members if m[2] is not None]
        skew_ms = max(skews) if skews else None
        # The host that waited LEAST arrived last: everyone else's wait
        # span was open until it showed up.
        slowest = min(members, key=lambda m: m[1])[0] if len(members) > 1 \
            else None
        entry = {"skew_ms": skew_ms, "slowest_host": slowest,
                 "waits": {m[0]: round(m[1], 6) for m in sorted(members)}}
        rkey = -1 if rnd is None else int(rnd)
        rounds.setdefault(rkey, {})[name] = entry

    postmortem = []
    for j in journals:
        hid = int(j["header"]["host_id"])
        align = aligner(j["header"])
        for f in j["flights"]:
            entry = {
                "host_id": hid, "kind": "flight",
                "reason": f.get("reason"),
                "t_aligned": round(align(f["t"]), 6),
            }
            # A crash that unwound through spans closed them before the
            # flight flush; the recorder stamps the innermost one here
            # so the postmortem still names where the failure struck.
            in_span = f.get("in_span")
            if isinstance(in_span, dict):
                entry["name"] = in_span.get("name")
                entry["cat"] = in_span.get("cat")
                entry["round"] = in_span.get("round")
                entry["error"] = in_span.get("error")
            postmortem.append(entry)
        for s in j["inflight"] + j["unmatched_opens"]:
            postmortem.append({
                "host_id": hid,
                # An unmatched open means the process never got to write
                # anything more — the hard-kill case; 'inflight' lines
                # come from the soft paths (SIGTERM, crash, quorum).
                "kind": ("inflight" if s.get("inflight")
                         else "died_inside"),
                "name": s.get("name"), "cat": s.get("cat"),
                "round": s.get("round"),
                "t0_aligned": round(align(s["t0"]), 6),
            })
    postmortem.sort(key=lambda p: p.get("t_aligned") or p.get("t0_aligned")
                    or 0.0)

    if host is not None:
        hosts = [h for h in hosts if h["host_id"] == host]
        totals = {k: v for k, v in totals.items() if k == host}
        postmortem = [p for p in postmortem if p["host_id"] == host]

    return {
        "hosts": hosts,
        "totals": {str(k): v for k, v in sorted(totals.items())},
        "rounds": {str(k): v for k, v in sorted(rounds.items())},
        "postmortem": postmortem,
    }


def render_text(summary: dict) -> str:
    lines = []
    lines.append("== hosts ==")
    for h in summary["hosts"]:
        lines.append(
            f"  host {h['host_id']}/{h['n_hosts']}: {h['spans']} spans, "
            f"{h['events']} events, clock offset "
            f"{h['clock_offset_s'] * 1e3:+.3f} ms "
            f"(+/- {h['clock_uncertainty_s'] * 1e3:.3f} ms) "
            f"[{os.path.basename(h['journal'])}]"
        )
    lines.append("== totals ==")
    for hid, t in summary["totals"].items():
        lines.append(
            f"  host {hid}: busy {t['busy_s']:.3f}s, dcn wait "
            f"{t['dcn_wait_s']:.3f}s (wait fraction {t['wait_fraction']:.1%},"
            f" critical-path share {t['critical_path_share']:.1%})"
        )
    if summary["rounds"]:
        lines.append("== barrier skew by round ==")
        for rnd, barriers in summary["rounds"].items():
            for name, e in sorted(barriers.items()):
                skew = ("n/a" if e["skew_ms"] is None
                        else f"{e['skew_ms']:.3f} ms")
                slow = ("" if e["slowest_host"] is None
                        else f", slowest host {e['slowest_host']}")
                lines.append(f"  round {rnd} {name}: skew {skew}{slow}")
    if summary["postmortem"]:
        lines.append("== postmortem ==")
        for p in summary["postmortem"]:
            if p["kind"] == "flight":
                struck = "" if not p.get("name") else (
                    f" while in {p['cat']}:{p['name']}"
                    + ("" if p.get("round") is None
                       else f" (round {p['round']})")
                )
                lines.append(
                    f"  host {p['host_id']}: flight recorder flushed "
                    f"({p['reason']}){struck}"
                )
            else:
                where = "" if p.get("round") is None else \
                    f" (round {p['round']})"
                verb = ("in flight" if p["kind"] == "inflight"
                        else "DIED INSIDE")
                lines.append(
                    f"  host {p['host_id']}: {verb} "
                    f"{p['cat']}:{p['name']}{where}"
                )
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        description="Stitch spans_*.jsonl host journals into one "
                    "cross-host timeline + skew/postmortem summary.",
    )
    ap.add_argument("paths", nargs="+",
                    help="span directories and/or journal files")
    ap.add_argument("--out", default=None,
                    help="write a Chrome trace-event JSON (perfetto)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary on stdout")
    ap.add_argument("--host", type=int, default=None,
                    help="restrict the summary to one host id")
    args = ap.parse_args(argv)

    try:
        paths = find_journals(args.paths)
    except FileNotFoundError as e:
        print(f"error: no such path: {e}", file=sys.stderr)
        return 2
    if not paths:
        print("error: no spans_*.jsonl journals found", file=sys.stderr)
        return 2
    journals = [load_journal(p) for p in paths]

    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(chrome_trace(journals), f)
        print(f"wrote {args.out} ({len(journals)} hosts) — load in "
              "https://ui.perfetto.dev", file=sys.stderr)

    summary = summarize(journals, host=args.host)
    if args.json:
        json.dump(summary, sys.stdout, indent=1)
        print()
    else:
        print(render_text(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
