"""Experiment: stage-1 activation orientation — NHWC vs HWNC.

MEASURED NEGATIVE RESULT (round 5, kept for the record): this isolated
chain shows HWNC 3.7x faster (92.8 -> 24.8 ms for 4 blocks fwd+bwd at
chunk 40), yet wiring the same orientation into the real model made the
sign_SGD ROUND 7% slower (2.72 -> 2.91 s/round) and left the bf16
fed/fed_quant rounds flat — the full round has consumers (stem boundary,
per-step vote, custom-vjp GroupNorm residual flow) that re-introduce
relayouts the isolated chain doesn't have. Third instance of the
round-3 lesson: isolated conv microbenches lie; only in-context
measurement decides.

Background: the round-5 sign_SGD trace showed ~240 ms/round of relayout
copies on the folded stage-1 activations — the grouped-conv backend emits
``{3,0,2,1}`` (batch in sublanes) while the GroupNorm reduces and
elementwise passes want ``{3,2,1,0}``, and XLA reconciles with
materialized (partly f32-upcast) copies whose consumers include the conv
weight-grad fusions (HLO-verified). HWNC removes them HERE but not in
the whole program.

Measures a 2-conv + 2-GroupNorm + relu residual block chain, vmapped over
per-client weights (the engine's structure), fwd+bwd, in both
orientations at the flagship shapes.

Usage: python scripts/exp_stage1_layout.py [n_chain] [chunk] [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

from distributed_learning_simulator_tpu.models.resnet import (  # noqa: E402
    pack_folded_kernel,
)


def timeit(fn, args, n):
    out = fn(*args)
    jax.device_get(out)
    t0 = time.perf_counter()
    acc = out
    for _ in range(n):
        acc = acc + fn(*args)
    jax.device_get(acc)
    return (time.perf_counter() - t0) / n


def gn_nhwc(x, g=32):
    b, h, wf, c2 = x.shape
    cpg = c2 // 2 // g
    x6 = x.reshape(b, h, wf, 2, g, cpg)
    x32 = x6.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2, 3, 5), keepdims=True)
    mean2 = jnp.mean(jnp.square(x32), axis=(1, 2, 3, 5), keepdims=True)
    rstd = jax.lax.rsqrt(jnp.maximum(mean2 - mean * mean, 0.0) + 1e-6)
    return ((x6 - mean) * rstd).astype(x.dtype).reshape(b, h, wf, c2)


def gn_hwnc(x, g=32):
    h, wf, b, c2 = x.shape
    cpg = c2 // 2 // g
    x6 = x.reshape(h, wf, b, 2, g, cpg)
    x32 = x6.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 1, 3, 5), keepdims=True)
    mean2 = jnp.mean(jnp.square(x32), axis=(0, 1, 3, 5), keepdims=True)
    rstd = jax.lax.rsqrt(jnp.maximum(mean2 - mean * mean, 0.0) + 1e-6)
    return ((x6 - mean) * rstd).astype(x.dtype).reshape(h, wf, b, c2)


def make_chain(orient: str, n_chain: int):
    if orient == "nhwc":
        dn = ("NHWC", "HWIO", "NHWC")
        gn = gn_nhwc
    else:
        dn = ("HWNC", "HWIO", "HWNC")
        gn = gn_hwnc

    def block(x, w):
        wp = pack_folded_kernel(w.astype(jnp.bfloat16))
        y = jax.lax.conv_general_dilated(
            x.astype(jnp.bfloat16), wp, (1, 1), "SAME",
            dimension_numbers=dn,
        )
        return jax.nn.relu(gn(y) + x)

    def one_client(ws, x):
        def loss(ws):
            y = x
            for w in ws:
                y = block(y, w)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        return jax.grad(loss)(ws)

    def run(ws_all, x_all):
        g = jax.vmap(one_client)(ws_all, x_all)
        return sum(jnp.sum(w.astype(jnp.float32)) for w in g)

    return jax.jit(run), n_chain


def main():
    n_chain = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 25
    key = jax.random.key(0)
    ws = [
        jax.random.normal(jax.random.fold_in(key, i),
                          (chunk, 3, 3, 64, 64), jnp.float32) * 0.05
        for i in range(n_chain)
    ]
    x_nhwc = jax.random.normal(key, (chunk, batch, 32, 16, 128),
                               jnp.bfloat16)
    # HWNC per-client logical shape [32, 16, batch, 128]
    x_hwnc = jnp.transpose(x_nhwc, (0, 2, 3, 1, 4))
    for orient, x in (("nhwc", x_nhwc), ("hwnc", x_hwnc)):
        fn, _ = make_chain(orient, n_chain)
        t = timeit(fn, (ws, x), 10)
        print(f"{orient}: {t * 1e3:8.2f} ms for {n_chain} blocks "
              f"fwd+bwd at chunk {chunk} x batch {batch}")


if __name__ == "__main__":
    main()
