"""Experiment: 9-tap shifted-einsum per-client conv vs vmapped lax.conv.

The vmapped per-client conv's backward lowers the client axis into a
base-dilated spatial dim (lhs_dilate=1x1xC) — XLA's generic slow path.
A 3x3 conv is also 9 shifted batched GEMMs: for tap (dy, dx),
``y += shift(x, dy, dx) @ w[dy, dx]`` with einsum 'cbhwk,cko->cbhwo'.
Autodiff then yields pure batched-GEMM gradients (no conv lowering at all).

Usage: python scripts/exp_tap_einsum.py [n_chain] [chunk] [batch]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

STAGES = [
    ("stage1", 32, 64, 64),
    ("stage2", 16, 128, 128),
    ("stage3", 8, 256, 256),
    ("stage4", 4, 512, 512),
]


def timeit(fn, args, n):
    out = fn(*args)
    jax.device_get(out)
    t0 = time.perf_counter()
    acc = out
    for _ in range(n):
        acc = acc + fn(*args)
    jax.device_get(acc)
    return (time.perf_counter() - t0) / n


def tap_conv(x, w):
    """Per-client 3x3 SAME conv as 9 shifted batched GEMMs.

    x: [C, B, H, W, cin], w: [C, 3, 3, cin, cout] -> [C, B, H, W, cout].
    """
    c, b, h, wd, cin = x.shape
    cout = w.shape[-1]
    xp = jnp.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1), (0, 0)))
    y = jnp.zeros((c, b, h, wd, cout), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            xs = jax.lax.dynamic_slice(
                xp, (0, 0, dy, dx, 0), (c, b, h, wd, cin)
            )
            y = y + jnp.einsum(
                "cbhwk,cko->cbhwo", xs, w[:, dy, dx],
                preferred_element_type=jnp.float32,
            )
    return y.astype(jnp.bfloat16)


def main():
    n_chain = int(sys.argv[1]) if len(sys.argv) > 1 else 10
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 40
    batch = int(sys.argv[3]) if len(sys.argv) > 3 else 25

    key = jax.random.key(0)
    for name, hw, cin, cout in STAGES:
        kx, kw, kg = jax.random.split(jax.random.fold_in(key, hw), 3)
        x = jax.random.normal(kx, (chunk, batch, hw, hw, cin), jnp.bfloat16)
        w = jax.random.normal(kw, (chunk, 3, 3, cin, cout), jnp.bfloat16)
        g = jax.random.normal(kg, (chunk, batch, hw, hw, cout), jnp.bfloat16)

        # A: vmapped conv (baseline)
        def conv_one(xc, wc):
            return jax.lax.conv_general_dilated(
                xc, wc, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )

        def loss_a(w_, x_):
            y = jax.vmap(conv_one)(x_, w_)
            return jnp.sum((y * g).astype(jnp.float32))

        def run_a(w_, x_):
            gw, gx = jax.grad(loss_a, argnums=(0, 1))(w_, x_)
            return jnp.sum(gw.astype(jnp.float32)) + jnp.sum(
                gx.astype(jnp.float32)
            )

        t_a = timeit(jax.jit(run_a), (w, x), n_chain)

        # D: tap-einsum
        def loss_d(w_, x_):
            y = tap_conv(x_, w_)
            return jnp.sum((y * g).astype(jnp.float32))

        def run_d(w_, x_):
            gw, gx = jax.grad(loss_d, argnums=(0, 1))(w_, x_)
            return jnp.sum(gw.astype(jnp.float32)) + jnp.sum(
                gx.astype(jnp.float32)
            )

        t_d = timeit(jax.jit(run_d), (w, x), n_chain)

        # Forward-only comparison too (fwd matters for eval + fwd pass).
        t_af = timeit(
            jax.jit(lambda w_, x_: jnp.sum(
                jax.vmap(conv_one)(x_, w_).astype(jnp.float32))),
            (w, x), n_chain,
        )
        t_df = timeit(
            jax.jit(lambda w_, x_: jnp.sum(
                tap_conv(x_, w_).astype(jnp.float32))),
            (w, x), n_chain,
        )
        print(
            f"{name}: fwd+bwd vmap-conv {t_a*1e3:7.2f} ms, tap-einsum "
            f"{t_d*1e3:7.2f} ms | fwd-only conv {t_af*1e3:6.2f} ms, "
            f"tap {t_df*1e3:6.2f} ms"
        )


if __name__ == "__main__":
    main()
